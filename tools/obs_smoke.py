"""obs-smoke: prove the observability plumbing end to end on CPU.

Runs a tiny board through the real CLI with `--run-report`,
`--metrics-port 0`, and `--trace-spans`, then validates ALL outputs:

  * the run report parses as schema gol-run-report/1 and contains at
    least one chunk record with wall/turns/CUPS populated, bracketed by
    run_start/run_end;
  * the `/metrics` endpoint serves parseable Prometheus text including
    the engine turn/CUPS gauges and the wire/server counter families;
  * the span export is a valid Chrome trace-event document whose
    controller.run / engine.run / engine.chunk spans share one trace id
    with correct parent links;
  * every metric family in the registry matches the Prometheus naming
    regex and carries the gol_ prefix;
  * `--profile-dir` produces loadable jax.profiler artifacts (an
    .xplane.pb plus a Perfetto trace.json.gz that parses), and the
    gol_profile_*/gol_dev_*/gol_compile_* families show up in
    /metrics with the capture counted;
  * `/healthz` carries the device-telemetry fields (device_kind,
    live_bytes, compile_count) and `/profile` serves capture status;
  * tools/perf_compare.py round-trips: exit 0 on identical synthetic
    reports, nonzero on an injected 20% CUPS drop.

Runs IN-PROCESS (main() is called, not subprocessed) so the ephemeral
metrics port is discoverable without output scraping, and stays inside
the tier-1 time budget. Exit 0 = pass.

    make obs-smoke      # JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
import urllib.request

# Runnable as `python tools/obs_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def main() -> int:
    tmpdir = tempfile.mkdtemp(prefix="gol_obs_smoke_")
    report = os.path.join(tmpdir, "run.jsonl")
    spans_path = os.path.join(tmpdir, "spans.json")
    profile_dir = os.path.join(tmpdir, "profile")

    from gol_tpu.main import main as gol_main

    # --profile-turns well under the run length: the capture consumes
    # its turns as traced chunks, and untraced chunk records must
    # remain for the report checks below.
    rc = gol_main(["-w", "64", "-h", "64", "--turns", "64",
                   "--rle", "rpentomino", "--headless", "-t", "1",
                   "--run-report", report, "--metrics-port", "0",
                   "--trace-spans", spans_path,
                   "--profile-dir", profile_dir,
                   "--profile-turns", "8"])
    if rc != 0:
        print(f"obs-smoke: CLI run failed rc={rc}", file=sys.stderr)
        return 1

    # ---- run report ----------------------------------------------------
    from gol_tpu.obs.timeline import read_report

    recs = list(read_report(report))  # raises on any schema violation
    events = [r["event"] for r in recs]
    chunks = [r for r in recs if r["event"] == "chunk"]
    problems = []
    if events[0] != "run_start" or events[-1] != "run_end":
        problems.append(f"bad bookends: {events[:1]} ... {events[-1:]}")
    if not chunks:
        problems.append("no chunk records")
    for c in chunks:
        if c["turns"] <= 0 or c["wall_s"] < 0 or c["cups"] < 0:
            problems.append(f"bad chunk record: {c}")
    if recs and recs[-1]["event"] == "run_end" and recs[-1]["turn"] != 64:
        problems.append(f"run_end turn {recs[-1]['turn']} != 64")

    # ---- /metrics ------------------------------------------------------
    from gol_tpu.obs.http import last_server

    srv = last_server()
    if srv is None:
        problems.append("metrics server did not start")
    else:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        for needle in ("# TYPE gol_engine_turn gauge",
                       "# TYPE gol_engine_cups gauge",
                       "# TYPE gol_server_requests_total counter",
                       "# TYPE gol_wire_bytes_total counter",
                       "gol_engine_chunk_seconds_bucket",
                       # PR 4 device/compile/profiler families
                       "# TYPE gol_dev_live_bytes gauge",
                       "# TYPE gol_dev_peak_bytes gauge",
                       "# TYPE gol_dev_mem_supported gauge",
                       "# TYPE gol_dev_devices gauge",
                       "# TYPE gol_compile_total counter",
                       "# TYPE gol_compile_cache_hits_total counter",
                       "# TYPE gol_compile_cache_misses_total counter",
                       "# TYPE gol_compile_seconds histogram",
                       "# TYPE gol_compile_step_signatures_total counter",
                       "# TYPE gol_profile_captures_total counter",
                       "# TYPE gol_profile_armed gauge",
                       # wire codec frame families
                       "# TYPE gol_wire_frames_total counter",
                       "# TYPE gol_wire_frame_bytes_total counter",
                       "# TYPE gol_wire_bytes_saved_total counter",
                       "# TYPE gol_wire_compression_ratio gauge",
                       "# TYPE gol_wire_encode_seconds histogram",
                       "# TYPE gol_wire_decode_seconds histogram",
                       'gol_wire_frames_total{codec="packed"}',
                       'gol_wire_frames_total{codec="xrle"}',
                       # PR 8 serving-SLO families (pre-seeded in the
                       # catalog, so they expose even before traffic)
                       "# TYPE gol_rpc_latency_ms gauge",
                       "# TYPE gol_slo_breaches_total counter",
                       "# TYPE gol_fleet_quantum_latency_ms gauge",
                       "# TYPE gol_fleet_queue_depth gauge",
                       "# TYPE gol_fleet_queue_wait_ms gauge",
                       "# TYPE gol_fleet_staleness_ms gauge",
                       "# TYPE gol_runs_destroyed_total counter",
                       'gol_rpc_latency_ms{kind="client",'
                       'method="unknown",q="p50"}',
                       'gol_rpc_latency_ms{kind="handler",'
                       'method="unknown",q="p99"}',
                       'gol_fleet_queue_wait_ms{q="p95"}',
                       # PR 9 mesh/halo + device-census families
                       # (axis children pre-seeded in the catalog)
                       "# TYPE gol_mesh_devices gauge",
                       "# TYPE gol_mesh_shards gauge",
                       "# TYPE gol_mesh_axis_size gauge",
                       "# TYPE gol_halo_exchanges_total counter",
                       "# TYPE gol_halo_bytes_total counter",
                       "# TYPE gol_halo_exchange_seconds histogram",
                       "# TYPE gol_shard_imbalance_ratio gauge",
                       "# TYPE gol_dev_kind_devices gauge",
                       "# TYPE gol_dev_mem_stats_supported gauge",
                       'gol_halo_bytes_total{axis="rows"}',
                       # PR 16 fleet telemetry plane (member-side
                       # snapshot export + registry rollups + tsdb +
                       # alerting + audit — all pre-seeded in the
                       # catalog, so they expose on every process)
                       "# TYPE gol_fed_snapshot_bytes gauge",
                       "# TYPE gol_fed_snapshot_total counter",
                       'gol_fed_snapshot_total{kind="full"}',
                       'gol_fed_snapshot_total{kind="delta"}',
                       'gol_fed_snapshot_dropped_total{family="quantum"}',
                       'gol_fed_snapshot_dropped_total{family="events"}',
                       "# TYPE gol_fed_snapshot_ingested_total counter",
                       "# TYPE gol_fed_agg_runs_resident gauge",
                       "# TYPE gol_fed_agg_queue_depth gauge",
                       "# TYPE gol_fed_agg_cups gauge",
                       'gol_fed_agg_staleness_ms{q="p99"}',
                       "# TYPE gol_fed_agg_imbalance_ratio gauge",
                       "# TYPE gol_fed_agg_members_reporting gauge",
                       "# TYPE gol_fed_agg_slo_breaches_total gauge",
                       "# TYPE gol_fed_agg_dev_live_bytes gauge",
                       'gol_fed_agg_payload_bytes{q="p50"}',
                       "# TYPE gol_tsdb_series gauge",
                       "# TYPE gol_tsdb_points_total gauge",
                       "# TYPE gol_tsdb_evictions_total gauge",
                       'gol_alerts_active{rule="member-death"}',
                       'gol_alerts_active{rule="queue-depth"}',
                       'gol_alerts_fired_total{rule="member-death"}',
                       'gol_audit_records_total{kind="member_death"}',
                       'gol_audit_records_total{kind="quarantine"}',
                       # PR 19 usage metering & capacity attribution
                       # (aggregate families only — per-run detail
                       # lives on the /healthz usage doc, PR-8
                       # cardinality posture)
                       "# TYPE gol_usage_runs_tracked gauge",
                       "# TYPE gol_usage_wall_us_total counter",
                       "# TYPE gol_usage_flushes_total counter",
                       "# TYPE gol_usage_untracked_total counter",
                       "# TYPE gol_capacity_free_bytes gauge",
                       "# TYPE gol_capacity_admissible_runs gauge",
                       "# TYPE gol_capacity_cups_headroom gauge",
                       "# TYPE gol_fed_agg_usage_runs_tracked gauge",
                       "# TYPE gol_fed_agg_usage_admissible_runs gauge",
                       "# TYPE gol_fed_agg_usage_cups_headroom gauge"):
            if needle not in body:
                problems.append(f"/metrics missing {needle!r}")
        if 'gol_profile_captures_total{status="ok"} 1' not in body:
            problems.append("profile capture not counted in /metrics")
        for line in body.splitlines():
            if line.startswith("gol_engine_turn "):
                if float(line.split()[-1]) != 64:
                    problems.append(f"engine turn gauge: {line!r}")
                break
        else:
            problems.append("no gol_engine_turn sample")
        base_url = srv.url.rsplit("/", 1)[0]
        # /metrics.json must carry the same gol_fed_* telemetry
        # families as the text exposition (federated members serve
        # their per-member values through this path).
        mjson = json.loads(urllib.request.urlopen(
            base_url + "/metrics.json", timeout=10).read().decode())
        for fam in ("gol_fed_snapshot_bytes", "gol_fed_snapshot_total",
                    "gol_fed_agg_runs_resident",
                    "gol_fed_agg_imbalance_ratio",
                    "gol_tsdb_series", "gol_alerts_active",
                    "gol_audit_records_total",
                    "gol_usage_runs_tracked",
                    "gol_fed_agg_usage_runs_tracked"):
            if fam not in mjson:
                problems.append(f"/metrics.json missing {fam!r}")
        alerts_rules = {v["labels"].get("rule")
                        for v in mjson.get("gol_alerts_active",
                                           {}).get("values", [])}
        if not {"member-death", "queue-depth"} <= alerts_rules:
            problems.append(
                f"/metrics.json gol_alerts_active rules: {alerts_rules}")
        healthz = json.loads(urllib.request.urlopen(
            base_url + "/healthz", timeout=10).read().decode())
        for field in ("device_kind", "live_bytes", "compile_count",
                      "runs", "slo", "mesh"):
            if field not in healthz:
                problems.append(f"/healthz missing {field!r}")
        if healthz.get("device_kind") != "cpu":
            problems.append(f"/healthz device_kind: {healthz!r}")
        # The engine stamps its mesh geometry at run start; a 1-thread
        # CPU run is a 1-device, 1-shard mesh.
        mesh_f = healthz.get("mesh") or {}
        if mesh_f.get("devices") != 1 or mesh_f.get("shards") != 1:
            problems.append(f"/healthz mesh geometry: {mesh_f!r}")
        prof_status = json.loads(urllib.request.urlopen(
            base_url + "/profile", timeout=10).read().decode())
        if prof_status.get("captures_ok") != 1 \
                or prof_status.get("last", {}).get("status") != "ok":
            problems.append(f"/profile status: {prof_status!r}")
        srv.close()

    # ---- profiler artifacts -------------------------------------------
    import glob
    import gzip

    xplanes = glob.glob(os.path.join(profile_dir, "**", "*.xplane.pb"),
                        recursive=True)
    perfetto = glob.glob(os.path.join(profile_dir, "**",
                                      "*.trace.json.gz"), recursive=True)
    if not xplanes:
        problems.append("no .xplane.pb profiler artifact")
    if not perfetto:
        problems.append("no Perfetto trace.json.gz profiler artifact")
    else:
        try:
            with gzip.open(perfetto[0]) as f:
                tdoc = json.load(f)
            if not tdoc.get("traceEvents"):
                problems.append("Perfetto trace has no traceEvents")
        except (OSError, ValueError) as e:
            problems.append(f"Perfetto trace unloadable: {e}")

    # ---- perf_compare round-trip --------------------------------------
    import perf_compare

    def _bench_line(value):
        return json.dumps({"metric": "cell-updates/sec (smoke torus)",
                           "value": value, "unit": "cell-updates/s",
                           "vs_baseline": None, "detail": {}})

    same_a = os.path.join(tmpdir, "bench_a.jsonl")
    same_b = os.path.join(tmpdir, "bench_b.jsonl")
    dropped = os.path.join(tmpdir, "bench_drop.jsonl")
    with open(same_a, "w") as f:
        f.write(_bench_line(1.0e12) + "\n")
    with open(same_b, "w") as f:
        f.write(_bench_line(1.0e12) + "\n")
    with open(dropped, "w") as f:
        f.write(_bench_line(0.8e12) + "\n")
    if perf_compare.main([same_a, same_b]) != 0:
        problems.append("perf_compare: identical reports did not pass")
    if perf_compare.main([same_a, dropped]) == 0:
        problems.append("perf_compare: 20% CUPS drop did not fail")

    # ---- span export ---------------------------------------------------
    from gol_tpu.obs import trace

    n_span_events = 0
    if not os.path.exists(spans_path):
        problems.append("span export was not written")
    else:
        try:
            with open(spans_path, encoding="utf-8") as f:
                doc = json.load(f)
            trace.validate_chrome(doc)
            by_name = {}
            for evd in doc["traceEvents"]:
                if evd["ph"] in ("X", "B"):
                    n_span_events += 1
                    by_name.setdefault(evd["name"], []).append(evd["args"])
            for needed in ("controller.run", "engine.run", "engine.chunk"):
                if needed not in by_name:
                    problems.append(f"span export missing {needed!r}")
            if not problems:
                ctrl = by_name["controller.run"][0]
                erun = by_name["engine.run"][0]
                if erun["trace_id"] != ctrl["trace_id"] \
                        or erun.get("parent_id") != ctrl["span_id"]:
                    problems.append("engine.run not parented under "
                                    "controller.run")
                for ch in by_name["engine.chunk"]:
                    if ch["trace_id"] != ctrl["trace_id"] \
                            or ch.get("parent_id") != erun["span_id"]:
                        problems.append("engine.chunk not parented "
                                        "under engine.run")
                        break
        except (ValueError, KeyError) as e:
            problems.append(f"span export invalid: {e}")

    # ---- catalog naming ------------------------------------------------
    from gol_tpu.obs.metrics import REGISTRY

    for name in REGISTRY.families():
        if not PROM_NAME_RE.match(name):
            problems.append(f"metric name violates Prometheus regex: "
                            f"{name!r}")
        if not name.startswith("gol_"):
            problems.append(f"metric name missing gol_ prefix: {name!r}")

    if problems:
        for p in problems:
            print(f"obs-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    print(f"obs-smoke: OK — {len(chunks)} chunk record(s), "
          f"/metrics served {len(body)} bytes, "
          f"{n_span_events} span event(s), "
          f"{len(REGISTRY.families())} metric families named cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
