"""obs-smoke: prove the observability plumbing end to end on CPU.

Runs a tiny board through the real CLI with `--run-report` and
`--metrics-port 0`, then validates BOTH outputs:

  * the run report parses as schema gol-run-report/1 and contains at
    least one chunk record with wall/turns/CUPS populated, bracketed by
    run_start/run_end;
  * the `/metrics` endpoint serves parseable Prometheus text including
    the engine turn/CUPS gauges and the wire/server counter families.

Runs IN-PROCESS (main() is called, not subprocessed) so the ephemeral
metrics port is discoverable without output scraping, and stays inside
the tier-1 time budget. Exit 0 = pass.

    make obs-smoke      # JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import os
import sys
import tempfile
import urllib.request

# Runnable as `python tools/obs_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    report = os.path.join(
        tempfile.mkdtemp(prefix="gol_obs_smoke_"), "run.jsonl")

    from gol_tpu.main import main as gol_main

    rc = gol_main(["-w", "64", "-h", "64", "--turns", "64",
                   "--rle", "rpentomino", "--headless", "-t", "1",
                   "--run-report", report, "--metrics-port", "0"])
    if rc != 0:
        print(f"obs-smoke: CLI run failed rc={rc}", file=sys.stderr)
        return 1

    # ---- run report ----------------------------------------------------
    from gol_tpu.obs.timeline import read_report

    recs = list(read_report(report))  # raises on any schema violation
    events = [r["event"] for r in recs]
    chunks = [r for r in recs if r["event"] == "chunk"]
    problems = []
    if events[0] != "run_start" or events[-1] != "run_end":
        problems.append(f"bad bookends: {events[:1]} ... {events[-1:]}")
    if not chunks:
        problems.append("no chunk records")
    for c in chunks:
        if c["turns"] <= 0 or c["wall_s"] < 0 or c["cups"] < 0:
            problems.append(f"bad chunk record: {c}")
    if recs and recs[-1]["event"] == "run_end" and recs[-1]["turn"] != 64:
        problems.append(f"run_end turn {recs[-1]['turn']} != 64")

    # ---- /metrics ------------------------------------------------------
    from gol_tpu.obs.http import last_server

    srv = last_server()
    if srv is None:
        problems.append("metrics server did not start")
    else:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        for needle in ("# TYPE gol_engine_turn gauge",
                       "# TYPE gol_engine_cups gauge",
                       "# TYPE gol_server_requests_total counter",
                       "# TYPE gol_wire_bytes_total counter",
                       "gol_engine_chunk_seconds_bucket"):
            if needle not in body:
                problems.append(f"/metrics missing {needle!r}")
        for line in body.splitlines():
            if line.startswith("gol_engine_turn "):
                if float(line.split()[-1]) != 64:
                    problems.append(f"engine turn gauge: {line!r}")
                break
        else:
            problems.append("no gol_engine_turn sample")
        srv.close()

    if problems:
        for p in problems:
            print(f"obs-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    print(f"obs-smoke: OK — {len(chunks)} chunk record(s), "
          f"/metrics served {len(body)} bytes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
