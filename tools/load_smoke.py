"""load-smoke: a small concurrent load generator for a fleet server.

Drives N client threads against a live wire server, each looping the
canonical serving cycle — CreateRun -> AttachRun -> GetView -> CFput
(pause) -> DestroyRun — and recording the client-observed wall latency
of every call, per method. The numbers come from the caller's own
clock (time.monotonic around each round trip), so they are END-TO-END:
connect + request + server queue/accept wait + handler + reply.

`--viewers N` adds the broadcast-tier population: N mostly-idle
Subscribe spectators of one watched run, parked in a `ViewerPool` that
drains (and discards, without decoding) the pushed epoch-stream bytes
on a single selectors thread — the C10k shape `bench.py --broadcast`
scales to 10k+. Viewers and the cycle load can run together: idle
spectators must not degrade the active control-plane SLOs.

Consumers:

  * `bench.py --load` imports `run_load` for the gated
    `rpc p50/p99 ms (load, <Method>)` metrics; `bench.py --broadcast`
    imports `open_viewers`/`ViewerPool` for its spectator population
    (see `make load-smoke` / `make broadcast-smoke`);
  * standalone, it load-tests ANY reachable server:

        python tools/load_smoke.py --address host:8765 --clients 8
        python tools/load_smoke.py --viewers 2000

    With no --address it starts a private in-process fleet server on
    an ephemeral port, which makes the zero-argument invocation a
    self-contained smoke (exit 0 = every cycle completed, nonzero on
    any error).

Kept deliberately small-N by default: the point is exercising the
serving path's SLO instrumentation honestly, not saturating a laptop.
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

# Runnable as `python tools/load_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The cycle's methods, in call order (also the report ordering).
CYCLE_METHODS = ("CreateRun", "AttachRun", "GetView", "CFput",
                 "DestroyRun")


def _worker(address: str, worker_id: int, cycles: int, board: int,
            view_cells: int, timeout: float,
            samples: Dict[str, List[float]], errors: List[str],
            lock: threading.Lock) -> None:
    from gol_tpu.client import RemoteEngine
    from gol_tpu.engine import FLAG_PAUSE

    eng = RemoteEngine(address, timeout=timeout)
    local: Dict[str, List[float]] = {m: [] for m in CYCLE_METHODS}
    for cycle in range(cycles):
        try:
            t0 = time.monotonic()
            rec = eng.create_run(board, board)
            local["CreateRun"].append(time.monotonic() - t0)
            rid = rec["run_id"]

            t0 = time.monotonic()
            bound = eng.attach_run(rid)
            local["AttachRun"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            bound.get_view(view_cells)
            local["GetView"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            bound.cf_put(FLAG_PAUSE)
            local["CFput"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            eng.destroy_run(rid)
            local["DestroyRun"].append(time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            with lock:
                errors.append(
                    f"worker {worker_id} cycle {cycle}: "
                    f"{type(e).__name__}: {e}")
            return
    with lock:
        for m, vals in local.items():
            samples.setdefault(m, []).extend(vals)


def run_load(address: str, *, clients: int = 4, cycles: int = 8,
             board: int = 64, view_cells: int = 4096,
             timeout: float = 30.0) -> dict:
    """Drive `clients` concurrent cycle loops against `address`.

    Returns {"samples": {method: [seconds, ...]}, "errors": [...],
    "clients": N, "cycles": M, "wall_s": total}. A worker stops its
    remaining cycles on the first error (recorded in "errors"), so a
    clean run has exactly clients*cycles samples per method.
    """
    samples: Dict[str, List[float]] = {}
    errors: List[str] = []
    lock = threading.Lock()
    threads = [
        threading.Thread(
            target=_worker,
            args=(address, i, cycles, board, view_cells, timeout,
                  samples, errors, lock),
            name=f"gol-load-{i}", daemon=True)
        for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout * cycles * len(CYCLE_METHODS))
    return {"samples": samples, "errors": errors, "clients": clients,
            "cycles": cycles, "wall_s": round(time.monotonic() - t0, 3)}


class ViewerPool:
    """N parked Subscribe spectators on one selectors thread.

    Each added `ViewSubscription`'s socket is drained byte-wise (recv
    + discard, no decode) so the subscribers look idle to the server —
    the gateway keeps pushing, the kernel buffers never fill, and the
    client process spends ~zero CPU per viewer. Byte/EOF counts are
    the only accounting; frame-level verification belongs to the few
    fully-decoding tracked viewers the bench runs alongside."""

    def __init__(self) -> None:
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._lock = threading.Lock()
        self._pending: List = []
        self._subs: Dict[int, object] = {}
        self.bytes_received = 0
        self.closed_count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="gol-viewer-pool", daemon=True)
        self._thread.start()

    def add(self, sub) -> None:
        """Park one ViewSubscription (ownership transfers here)."""
        with self._lock:
            self._pending.append(sub)
        self._poke()

    def alive(self) -> int:
        with self._lock:
            return len(self._subs) + len(self._pending)

    def stats(self) -> dict:
        with self._lock:
            return {"alive": len(self._subs) + len(self._pending),
                    "closed": self.closed_count,
                    "bytes": self.bytes_received}

    def close(self) -> None:
        self._stop.set()
        self._poke()
        self._thread.join(timeout=5.0)

    def _poke(self) -> None:
        try:
            self._wake_w.send(b"\x01")
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._sel.select(timeout=0.5)
            except OSError:
                break
            for key, _ in events:
                if key.data is None:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except OSError:
                        pass
                    continue
                self._drain(key.data)
            with self._lock:
                pending, self._pending = self._pending, []
            for sub in pending:
                try:
                    sub._sock.setblocking(False)
                    self._sel.register(
                        sub._sock, selectors.EVENT_READ, sub)
                except (OSError, ValueError):
                    self._dead(sub, registered=False)
                    continue
                with self._lock:
                    self._subs[sub._sock.fileno()] = sub
        # Teardown: hang every spectator up.
        for sub in list(self._subs.values()):
            self._dead(sub)
        with self._lock:
            for sub in self._pending:
                sub.close()
            self._pending = []
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except OSError:
            pass

    def _drain(self, sub) -> None:
        try:
            while True:
                data = sub._sock.recv(1 << 16)
                if not data:
                    self._dead(sub)
                    return
                with self._lock:
                    self.bytes_received += len(data)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._dead(sub)

    def _dead(self, sub, registered: bool = True) -> None:
        if registered:
            try:
                self._sel.unregister(sub._sock)
            except (KeyError, ValueError, OSError):
                pass
        with self._lock:
            self._subs.pop(sub._sock.fileno(), None)
            self.closed_count += 1
        sub.close()


def open_viewers(address: str, *, viewers: int, run_id: Optional[str],
                 view_cells: int = 4096, timeout: float = 30.0,
                 threads: int = 8):
    """Open `viewers` Subscribe upgrades bound to `run_id` and park
    them in a ViewerPool. Returns (pool, errors) — errors is the list
    of subscribe failures (each opener thread stops at its first)."""
    from gol_tpu.client import RemoteEngine

    pool = ViewerPool()
    errors: List[str] = []
    lock = threading.Lock()
    counter = [0]

    def opener() -> None:
        eng = RemoteEngine(address, timeout=timeout, run_id=run_id)
        while True:
            with lock:
                if counter[0] >= viewers or errors:
                    return
                counter[0] += 1
            try:
                pool.add(eng.subscribe(view_cells, timeout=timeout))
            except Exception as e:  # noqa: BLE001 — report, don't crash
                with lock:
                    errors.append(f"subscribe: {type(e).__name__}: {e}")
                return

    workers = [threading.Thread(target=opener, daemon=True,
                                name=f"gol-viewer-open-{i}")
               for i in range(max(1, min(threads, viewers)))]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=timeout * 4)
    return pool, errors


def summarize(samples: Dict[str, List[float]]) -> Dict[str, dict]:
    """{method: {count, p50_ms, p99_ms, max_ms}} via exact percentiles
    (small populations — no need for the streaming estimator here)."""
    from gol_tpu.obs import slo

    out: Dict[str, dict] = {}
    for method in CYCLE_METHODS:
        vals = samples.get(method) or []
        if not vals:
            continue
        p50, p99 = slo.exact_percentiles(vals, (0.50, 0.99))
        out[method] = {"count": len(vals),
                       "p50_ms": round(p50 * 1e3, 3),
                       "p99_ms": round(p99 * 1e3, 3),
                       "max_ms": round(max(vals) * 1e3, 3)}
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="concurrent create/attach/view/flag/destroy load "
                    "against a fleet server")
    ap.add_argument("--address", default="",
                    help="host:port of a running server (default: "
                         "start a private in-process fleet server)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--cycles", type=int, default=8,
                    help="cycles per client (default 8)")
    ap.add_argument("--board", type=int, default=64,
                    help="square board side per run (default 64)")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--viewers", type=int, default=0,
                    help="also park N mostly-idle broadcast subscribers "
                         "on one watched run for --hold seconds "
                         "(default 0 = none)")
    ap.add_argument("--view-cells", type=int, default=4096,
                    help="max_cells of the viewers' subscribed view")
    ap.add_argument("--hold", type=float, default=2.0,
                    help="seconds to hold the viewer population open")
    args = ap.parse_args(argv)

    server = engine = None
    address = args.address
    if not address:
        from gol_tpu.fleet.engine import FleetEngine
        from gol_tpu.server import EngineServer

        engine = FleetEngine(bucket_sizes=(64,), chunk_turns=2,
                             slot_base=8)
        server = EngineServer(port=0, host="127.0.0.1", engine=engine)
        server.start_background()
        address = f"127.0.0.1:{server.port}"
    pool = None
    ctl = watched = None
    viewer_errors: List[str] = []
    viewer_stats: Optional[dict] = None
    try:
        if args.viewers > 0:
            # Park the mostly-idle spectator population on one watched
            # run BEFORE the cycle load starts, so the active
            # control-plane latencies below are measured with the
            # broadcast tier live.
            from gol_tpu.client import RemoteEngine

            ctl = RemoteEngine(address, timeout=args.timeout)
            watched = ctl.create_run(args.board, args.board)["run_id"]
            pool, viewer_errors = open_viewers(
                address, viewers=args.viewers, run_id=watched,
                view_cells=args.view_cells, timeout=args.timeout)
        result = run_load(address, clients=args.clients,
                          cycles=args.cycles, board=args.board,
                          timeout=args.timeout)
        if pool is not None and not viewer_errors:
            time.sleep(max(0.0, args.hold))
            viewer_stats = pool.stats()
    finally:
        if pool is not None:
            pool.close()
        if ctl is not None and watched is not None:
            try:
                ctl.destroy_run(watched)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if engine is not None:
            engine.kill_prog()
        if server is not None:
            server.shutdown()
    table = summarize(result["samples"])
    summary = {"address": address, "wall_s": result["wall_s"],
               "clients": result["clients"],
               "cycles": result["cycles"], "methods": table,
               "errors": result["errors"]}
    if args.viewers > 0:
        summary["viewers"] = {"requested": args.viewers,
                              "hold_s": args.hold,
                              "stats": viewer_stats,
                              "errors": viewer_errors}
    print(json.dumps(summary, sort_keys=True))
    if result["errors"]:
        for e in result["errors"]:
            print(f"load-smoke: FAIL: {e}", file=sys.stderr)
        return 1
    missing = [m for m in CYCLE_METHODS if m not in table]
    if missing:
        print(f"load-smoke: FAIL: no samples for {missing}",
              file=sys.stderr)
        return 1
    if args.viewers > 0:
        for e in viewer_errors:
            print(f"load-smoke: FAIL: viewer: {e}", file=sys.stderr)
        if viewer_errors:
            return 1
        assert viewer_stats is not None
        if viewer_stats["closed"] or \
                viewer_stats["alive"] != args.viewers:
            print(f"load-smoke: FAIL: viewers dropped: {viewer_stats}",
                  file=sys.stderr)
            return 1
        if viewer_stats["bytes"] <= 0:
            print("load-smoke: FAIL: viewers received zero pushed "
                  "bytes", file=sys.stderr)
            return 1
        print(f"load-smoke: viewers OK — {args.viewers} subscriber(s) "
              f"held {args.hold}s, {viewer_stats['bytes']} pushed "
              "bytes drained")
    print(f"load-smoke: OK — {args.clients} client(s) x "
          f"{args.cycles} cycle(s) in {result['wall_s']}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
