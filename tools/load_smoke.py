"""load-smoke: a small concurrent load generator for a fleet server.

Drives N client threads against a live wire server, each looping the
canonical serving cycle — CreateRun -> AttachRun -> GetView -> CFput
(pause) -> DestroyRun — and recording the client-observed wall latency
of every call, per method. The numbers come from the caller's own
clock (time.monotonic around each round trip), so they are END-TO-END:
connect + request + server queue/accept wait + handler + reply.

Two consumers:

  * `bench.py --load` imports `run_load` to produce the gated
    `rpc p50/p99 ms (load, <Method>)` metrics against an in-process
    fleet server (see `make load-smoke`);
  * standalone, it load-tests ANY reachable server:

        python tools/load_smoke.py --address host:8765 --clients 8

    With no --address it starts a private in-process fleet server on
    an ephemeral port, which makes the zero-argument invocation a
    self-contained smoke (exit 0 = every cycle completed, nonzero on
    any error).

Kept deliberately small-N by default: the point is exercising the
serving path's SLO instrumentation honestly, not saturating a laptop.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

# Runnable as `python tools/load_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The cycle's methods, in call order (also the report ordering).
CYCLE_METHODS = ("CreateRun", "AttachRun", "GetView", "CFput",
                 "DestroyRun")


def _worker(address: str, worker_id: int, cycles: int, board: int,
            view_cells: int, timeout: float,
            samples: Dict[str, List[float]], errors: List[str],
            lock: threading.Lock) -> None:
    from gol_tpu.client import RemoteEngine
    from gol_tpu.engine import FLAG_PAUSE

    eng = RemoteEngine(address, timeout=timeout)
    local: Dict[str, List[float]] = {m: [] for m in CYCLE_METHODS}
    for cycle in range(cycles):
        try:
            t0 = time.monotonic()
            rec = eng.create_run(board, board)
            local["CreateRun"].append(time.monotonic() - t0)
            rid = rec["run_id"]

            t0 = time.monotonic()
            bound = eng.attach_run(rid)
            local["AttachRun"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            bound.get_view(view_cells)
            local["GetView"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            bound.cf_put(FLAG_PAUSE)
            local["CFput"].append(time.monotonic() - t0)

            t0 = time.monotonic()
            eng.destroy_run(rid)
            local["DestroyRun"].append(time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            with lock:
                errors.append(
                    f"worker {worker_id} cycle {cycle}: "
                    f"{type(e).__name__}: {e}")
            return
    with lock:
        for m, vals in local.items():
            samples.setdefault(m, []).extend(vals)


def run_load(address: str, *, clients: int = 4, cycles: int = 8,
             board: int = 64, view_cells: int = 4096,
             timeout: float = 30.0) -> dict:
    """Drive `clients` concurrent cycle loops against `address`.

    Returns {"samples": {method: [seconds, ...]}, "errors": [...],
    "clients": N, "cycles": M, "wall_s": total}. A worker stops its
    remaining cycles on the first error (recorded in "errors"), so a
    clean run has exactly clients*cycles samples per method.
    """
    samples: Dict[str, List[float]] = {}
    errors: List[str] = []
    lock = threading.Lock()
    threads = [
        threading.Thread(
            target=_worker,
            args=(address, i, cycles, board, view_cells, timeout,
                  samples, errors, lock),
            name=f"gol-load-{i}", daemon=True)
        for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout * cycles * len(CYCLE_METHODS))
    return {"samples": samples, "errors": errors, "clients": clients,
            "cycles": cycles, "wall_s": round(time.monotonic() - t0, 3)}


def summarize(samples: Dict[str, List[float]]) -> Dict[str, dict]:
    """{method: {count, p50_ms, p99_ms, max_ms}} via exact percentiles
    (small populations — no need for the streaming estimator here)."""
    from gol_tpu.obs import slo

    out: Dict[str, dict] = {}
    for method in CYCLE_METHODS:
        vals = samples.get(method) or []
        if not vals:
            continue
        p50, p99 = slo.exact_percentiles(vals, (0.50, 0.99))
        out[method] = {"count": len(vals),
                       "p50_ms": round(p50 * 1e3, 3),
                       "p99_ms": round(p99 * 1e3, 3),
                       "max_ms": round(max(vals) * 1e3, 3)}
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="concurrent create/attach/view/flag/destroy load "
                    "against a fleet server")
    ap.add_argument("--address", default="",
                    help="host:port of a running server (default: "
                         "start a private in-process fleet server)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--cycles", type=int, default=8,
                    help="cycles per client (default 8)")
    ap.add_argument("--board", type=int, default=64,
                    help="square board side per run (default 64)")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    server = engine = None
    address = args.address
    if not address:
        from gol_tpu.fleet.engine import FleetEngine
        from gol_tpu.server import EngineServer

        engine = FleetEngine(bucket_sizes=(64,), chunk_turns=2,
                             slot_base=8)
        server = EngineServer(port=0, host="127.0.0.1", engine=engine)
        server.start_background()
        address = f"127.0.0.1:{server.port}"
    try:
        result = run_load(address, clients=args.clients,
                          cycles=args.cycles, board=args.board,
                          timeout=args.timeout)
    finally:
        if engine is not None:
            engine.kill_prog()
        if server is not None:
            server.shutdown()
    table = summarize(result["samples"])
    print(json.dumps({"address": address, "wall_s": result["wall_s"],
                      "clients": result["clients"],
                      "cycles": result["cycles"], "methods": table,
                      "errors": result["errors"]}, sort_keys=True))
    if result["errors"]:
        for e in result["errors"]:
            print(f"load-smoke: FAIL: {e}", file=sys.stderr)
        return 1
    missing = [m for m in CYCLE_METHODS if m not in table]
    if missing:
        print(f"load-smoke: FAIL: no samples for {missing}",
              file=sys.stderr)
        return 1
    print(f"load-smoke: OK — {args.clients} client(s) x "
          f"{args.cycles} cycle(s) in {result['wall_s']}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
