"""fleet-mesh-smoke: prove the mesh-sharded fleet end to end.

Runs a reduced `bench.py --fleet --mesh` matrix IN-PROCESS on CPU
(8 forced host devices): the 1-way baseline and one 4-way leg, 64
resident 512² runs each. Then validates every surface the tentpole is
supposed to light up:

  * the emitted bench lines parse, are parity-clean (the 4-way board
    is BIT-IDENTICAL to the 1-device fleet's), stamp the true
    placement mesh (batch placement over 4 devices — never a bare
    jax.device_count()), and retired turns with ZERO new step
    signatures inside the measurement window;
  * the fleet_scaling_efficiency_pct line exists for the 4-way leg;
  * the gol_fleet_mesh_devices gauge and the per-device
    gol_fleet_device_resident_runs children are populated in the
    registry after the run;
  * `catalog.runs_doc()` (the /healthz runs summary) carries the
    mesh_devices stamp;
  * tools/perf_compare.py gates the captured lines against the
    committed BASELINE.json floors (per-device cups and
    fleet_scaling_efficiency_pct, higher is better).

Exit 0 = pass.

    make fleet-mesh-smoke     # part of the `make smoke` chain
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout

# Runnable as `python tools/fleet_mesh_smoke.py` from a bare clone: put
# the repo root (this file's parent's parent) ahead of tools/ on
# sys.path.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The legs need devices; force 8 virtual host devices strictly before
# any jax backend initialisation (same guard as bench.py --mesh).
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

SMOKE_WAYS = (1, 4)
SMOKE_RUNS = (64,)
SMOKE_SIZE = 512
SMOKE_WINDOW_S = 1.0


def main() -> int:
    import bench

    problems = []
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.bench_fleet_mesh(ways=SMOKE_WAYS,
                                    run_counts=SMOKE_RUNS,
                                    n=SMOKE_SIZE,
                                    window_s=SMOKE_WINDOW_S)
    captured = buf.getvalue()
    sys.stdout.write(captured)
    if rc != 0:
        problems.append(f"bench_fleet_mesh rc={rc} "
                        f"(parity/signature gate failed?)")

    # ---- bench lines ---------------------------------------------------
    recs = []
    for line in captured.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            recs.append(json.loads(line))
        except ValueError:
            problems.append(f"unparseable bench line: {line[:80]!r}")
    names = {r.get("metric", "") for r in recs}
    runs, n = SMOKE_RUNS[0], SMOKE_SIZE
    for needed in (
            f"aggregate cell-updates/sec (fleet-mesh, 1-way, "
            f"{runs} x {n}x{n} runs)",
            f"per-device cell-updates/sec (fleet-mesh, 4-way, "
            f"{runs} x {n}x{n} runs)",
            f"fleet_scaling_efficiency_pct (4-way, "
            f"{runs} x {n}x{n} runs)"):
        if needed not in names:
            problems.append(f"missing bench line {needed!r}")
    for r in recs:
        d = r.get("detail", {})
        if d.get("alive_parity") is not True:
            problems.append(f"parity not clean on {r.get('metric')!r}")
        if d.get("new_step_signatures_in_window"):
            problems.append(
                f"step signatures moved inside the window of "
                f"{r.get('metric')!r}")
        ways = d.get("ways")
        if ways == 4:
            if d.get("placement") != "batch":
                problems.append(f"4-way leg placement is "
                                f"{d.get('placement')!r}, want 'batch'")
            mesh = d.get("mesh") or {}
            if mesh.get("devices") != 4 \
                    or mesh.get("axes") != {"slots": 4}:
                problems.append(
                    f"bad placement mesh in detail: {mesh!r}")
        elif ways == 1 and d.get("devices") != 1:
            problems.append(
                f"1-way leg stamps devices={d.get('devices')!r} — the "
                f"placement mesh, not jax.device_count(), must be "
                f"reported")

    # ---- registry families hold real samples ---------------------------
    from gol_tpu.obs.metrics import REGISTRY

    samples = {}
    for line in REGISTRY.render_prometheus().splitlines():
        if line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            samples[key] = float(val)
        except ValueError:
            pass
    if samples.get("gol_fleet_mesh_devices", 0) <= 0:
        problems.append(
            f"gol_fleet_mesh_devices not populated: "
            f"{samples.get('gol_fleet_mesh_devices')}")
    dev_children = [k for k in samples
                    if k.startswith("gol_fleet_device_resident_runs{")]
    if len(dev_children) < 4:
        problems.append(
            f"per-device resident gauge has {len(dev_children)} "
            f"children, want >= 4 (one per placement device)")

    # ---- /healthz runs summary mesh stamp ------------------------------
    from gol_tpu.obs import catalog as obs_cat

    doc = obs_cat.runs_doc()
    if not doc.get("mesh_devices"):
        problems.append(f"runs_doc carries no mesh_devices: {doc!r}")

    # ---- perf_compare gate round-trip ----------------------------------
    import perf_compare

    tmpdir = tempfile.mkdtemp(prefix="gol_fleet_mesh_smoke_")
    out_path = os.path.join(tmpdir, "fleet_mesh.jsonl")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(captured)
    if perf_compare.main([os.path.join(_ROOT, "BASELINE.json"),
                          out_path]) != 0:
        problems.append("perf_compare gate failed on the fleet-mesh "
                        "legs")

    if problems:
        for p in problems:
            print(f"fleet-mesh-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    effs = [r["value"] for r in recs
            if str(r.get("metric", "")).startswith(
                "fleet_scaling_efficiency_pct")]
    print(f"fleet-mesh-smoke: OK — {len(recs)} fleet-mesh line(s), "
          f"4-way bit-identical to the 1-device fleet, "
          f"efficiency {effs[0] if effs else '?'}% on shared-core "
          f"virtual devices")
    return 0


if __name__ == "__main__":
    sys.exit(main())
