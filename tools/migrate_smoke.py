"""migrate-smoke: prove zero-downtime live migration end to end on CPU.

One acceptance scenario (PR 15), real member processes behind a real
in-process router:

  * three `--fleet --federate` servers register with a
    FederationRouter; one of them spawns with a one-shot
    `GOL_CHAOS=migrate_fail=redirect` armed in its own environment;
    runs created THROUGH the router are HRW-placed and parked at a
    target turn;
  * a clean `Rescale` live-migrates one run between the two clean
    members: the reply must report status ok, the router placement
    must flip to the target, the run must stay readable through the
    SAME router address at the SAME turn, bit-identical to a device
    torus replay of its seed — and a straggler call landing directly
    on the retired source must get the RETRYABLE "moved:" answer,
    never "unknown run";
  * the chaos member's FIRST Rescale must fail at the redirect
    boundary and roll back: the run stays listed on its source at its
    turn, board intact — and a SECOND Rescale of the SAME run must
    then succeed (rollback leaves the run fully re-migratable).

Exit 0 = pass.

    make migrate-smoke   # bench.py --migrate + gate, then this
"""

from __future__ import annotations

import os
import socket
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from federation_smoke import (  # noqa: E402  (tools-local import)
    FED_ENV, expected_board01, fail, spawn_member, wait_live,
    wait_member, wait_runs_at)


def _raw_call(addr: str, header: dict) -> dict:
    """One raw wire round trip — NO client retry layer, so a "moved:"
    answer surfaces instead of being transparently followed."""
    from gol_tpu import wire

    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=10.0) as s:
        wire.enable_nodelay(s)
        s.settimeout(10.0)
        wire.send_msg(s, header)
        resp, _ = wire.recv_msg(s)
    return resp


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    for var in ("GOL_CHAOS", "GOL_MIGRATE_DEADLINE",
                "GOL_MIGRATE_STALE"):
        os.environ.pop(var, None)
    os.environ.update(FED_ENV)

    from gol_tpu.client import RemoteEngine
    from gol_tpu.federation.router import FederationRouter

    tmpdir = tempfile.mkdtemp(prefix="gol_mig_smoke_")
    ckpt_root = os.path.join(tmpdir, "ck")
    target = 16
    mig_env = {"GOL_MIGRATE_DEADLINE": "120"}

    router = FederationRouter(port=0).start_background()
    procs = [spawn_member(tmpdir, ckpt_root, router.port,
                          extra_env=mig_env) for _ in range(2)]
    procs.append(spawn_member(
        tmpdir, ckpt_root, router.port,
        extra_env={**mig_env, "GOL_CHAOS": "migrate_fail=redirect"}))
    try:
        addrs = []
        for p in procs:
            addr = wait_member(p)
            if addr is None:
                return fail("a member never announced its port")
            addrs.append(addr)
        chaos_addr = addrs[-1]
        clean = addrs[:-1]
        if not wait_live(router, 3):
            return fail("registry never reached 3 live members")
        print(f"migrate-smoke: 3 members live behind router "
              f":{router.port} (migrate_fail=redirect armed on "
              f"{chaos_addr})", flush=True)

        cli = RemoteEngine(f"127.0.0.1:{router.port}", timeout=60.0)
        rng = np.random.default_rng(7)
        seeds = {}
        owners = {}
        # HRW owns placement; top up until the chaos member and at
        # least one clean member own a run each.
        for _ in range(8):
            rid = f"mig{len(seeds)}"
            seeds[rid] = (rng.random((64, 64)) < 0.3).astype(np.uint8)
            cli.create_run(64, 64, board=seeds[rid], run_id=rid,
                           ckpt_every=4, target_turn=target)
            owners = wait_runs_at(cli, sorted(seeds), target)
            if owners is None:
                return fail("runs never parked at their target turn")
            if (any(m == chaos_addr for m in owners.values())
                    and any(m in clean for m in owners.values())):
                break
        else:
            return fail("HRW never covered both member kinds")

        # ---- clean cutover ------------------------------------------
        rid = next(r for r in sorted(owners) if owners[r] in clean)
        src = owners[rid]
        dst = next(a for a in clean if a != src)
        rec = cli.rescale(rid, dst)
        if rec.get("status") != "ok" or rec.get("turn") != target:
            return fail(f"clean Rescale answered {rec}")
        runs, _ = cli.list_runs()
        now = {r["run_id"]: r for r in runs}[rid]
        if now["member"] != dst or now["turn"] != target:
            return fail(f"{rid} not authoritative on {dst} after the "
                        f"cutover: {now}")
        board, turn = cli.for_run(rid).get_world()
        if turn != target or not np.array_equal(
                (board != 0).astype(np.uint8),
                expected_board01(seeds[rid], target)):
            return fail(f"{rid} diverged from the device replay "
                        "oracle after the cutover")
        straggler = _raw_call(src, {"method": "Stats", "run_id": rid})
        if not str(straggler.get("error", "")).startswith("moved:"):
            return fail("retired source answered a straggler with "
                        f"{straggler!r}, wanted a retryable 'moved:'")
        print(f"migrate-smoke: {rid} cut over {src} -> {dst} "
              f"(downtime {rec['downtime_ms']} ms), oracle parity "
              "holds, straggler got moved:", flush=True)

        # ---- chaos rollback, then re-migrate ------------------------
        crid = next(r for r in sorted(owners)
                    if owners[r] == chaos_addr)
        cdst = clean[0]
        try:
            cli.rescale(crid, cdst)
            return fail("the armed migrate_fail=redirect Rescale "
                        "reported success")
        except RuntimeError as e:
            if "rolled back" not in str(e):
                return fail(f"armed Rescale failed oddly: {e}")
        runs, _ = cli.list_runs()
        now = {r["run_id"]: r for r in runs}.get(crid)
        if now is None or now["member"] != chaos_addr \
                or now["turn"] != target:
            return fail(f"rollback did not leave {crid} intact on "
                        f"{chaos_addr}: {now}")
        board, turn = cli.for_run(crid).get_world()
        if turn != target or not np.array_equal(
                (board != 0).astype(np.uint8),
                expected_board01(seeds[crid], target)):
            return fail(f"{crid} board corrupted by the rollback")
        rec = cli.rescale(crid, cdst)   # the one-shot is spent
        if rec.get("status") != "ok":
            return fail(f"post-rollback Rescale answered {rec}")
        runs, _ = cli.list_runs()
        now = {r["run_id"]: r for r in runs}[crid]
        if now["member"] != cdst or now["turn"] != target:
            return fail(f"{crid} not on {cdst} after the "
                        f"post-rollback cutover: {now}")
        print(f"migrate-smoke: {crid} rolled back at redirect, "
              f"stayed intact on {chaos_addr}, then cut over clean "
              f"to {cdst}", flush=True)
        print("migrate-smoke: PASS", flush=True)
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(10)
        router.shutdown()


if __name__ == "__main__":
    rc = main()
    # os._exit dodges the known XLA daemon-thread teardown abort;
    # every gate already flushed its verdict.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
