"""journal-smoke: prove the event-sourced run journal end to end.

One acceptance scenario (PR 17), real federated member processes
behind a real in-process router, sharing ONE checkpoint root and ONE
journal root:

  * a fleet run is created through the router and driven toward turn
    1000 with checkpoint-cadence board digests journaling along the
    way; a SetRule lands mid-flight (the rule event must replay at its
    exact recorded turn);
  * the run's owner is SIGKILLed mid-drive: a survivor adopts it from
    the shared checkpoint root and — because the journal root is
    shared too — RESUMES the same hash chain in place (link event,
    quarantine-restore event, then digests under the new owner), after
    truncating any torn line the kill left behind;
  * once the run re-parks at turn 1000, `tools/replay_audit.py`
    chain-verifies the journal and deterministically replays it,
    asserting a bit-identical board_sha256 at EVERY digest event —
    before the kill, across the rewind, and after adoption;
  * the audit must exit 0 with gol_replay_divergence_total == 0, and
    the journal must contain the create, the rule change, and the
    adoption link to count as having exercised the full story.

Exit 0 = pass.

    make journal-smoke
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.federation_smoke import (  # noqa: E402
    FED_ENV, spawn_member, wait_member, wait_live, wait_runs_at)

TARGET = 1000
CKPT_EVERY = 100
RULE_CHANGE = "B36/S23"


def fail(msg: str) -> int:
    print(f"journal-smoke: FAIL — {msg}", flush=True)
    return 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("GOL_CHAOS", None)
    os.environ.update(FED_ENV)

    from gol_tpu import journal
    from gol_tpu.client import RemoteEngine
    from gol_tpu.federation.router import FederationRouter

    tmpdir = tempfile.mkdtemp(prefix="gol_journal_smoke_")
    ckpt_root = os.path.join(tmpdir, "ck")
    journal_root = os.path.join(tmpdir, "journal")
    n_members = 2
    jenv = {"GOL_JOURNAL": journal_root}

    router = FederationRouter(port=0).start_background()
    procs = [spawn_member(tmpdir, ckpt_root, router.port,
                          ckpt_every=CKPT_EVERY, extra_env=jenv)
             for _ in range(n_members)]
    members = {}
    try:
        for p in procs:
            addr = wait_member(p)
            if addr is None:
                return fail("a member never announced its port")
            members[addr] = p
        if not wait_live(router, n_members):
            return fail("registry never reached "
                        f"{n_members} live members")
        print(f"journal-smoke: {n_members} members live behind "
              f"router :{router.port}", flush=True)

        cli = RemoteEngine(f"127.0.0.1:{router.port}", timeout=60.0)
        rid = "jbox0"
        rng = np.random.default_rng(17)
        seed = (rng.random((64, 64)) < 0.3).astype(np.uint8)
        cli.create_run(64, 64, board=seed, run_id=rid,
                       ckpt_every=CKPT_EVERY, target_turn=TARGET)

        # Rule change mid-flight: wait for some progress first so the
        # event lands at a nonzero turn, then re-target the evolution.
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            runs, _ = cli.list_runs()
            rec = next((r for r in runs if r["run_id"] == rid), None)
            if rec is not None and rec["turn"] > 0:
                break
            time.sleep(0.1)
        cli.set_rule(rid, RULE_CHANGE)
        print("journal-smoke: SetRule applied mid-flight", flush=True)

        # SIGKILL the owner mid-drive (after at least one checkpoint
        # under the new rule so adoption restores INTO the rule-changed
        # history).
        owner = None
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            runs, _ = cli.list_runs()
            rec = next((r for r in runs if r["run_id"] == rid), None)
            if rec is not None and rec["turn"] >= 2 * CKPT_EVERY:
                owner = rec.get("member")
                break
            time.sleep(0.1)
        if not owner or owner not in members:
            return fail(f"never saw {rid} progress past "
                        f"{2 * CKPT_EVERY} turns (owner {owner!r})")
        os.kill(members[owner].pid, signal.SIGKILL)
        members[owner].wait(10)
        print(f"journal-smoke: SIGKILLed {owner} at >= "
              f"{2 * CKPT_EVERY} turns", flush=True)

        owners2 = wait_runs_at(cli, [rid], TARGET, timeout=300.0)
        if owners2 is None:
            return fail("run never re-parked at the target after "
                        "the kill")
        if owners2[rid] == owner:
            return fail("run still listed on the dead member")
        print(f"journal-smoke: {rid} re-homed to {owners2[rid]} and "
              f"parked at turn {TARGET}", flush=True)

        # The shared-root journal must carry the whole story in ONE
        # continuous chain: create, the rule event, the adoption link.
        jpath = os.path.join(journal_root,
                             journal._safe_name(rid) + ".jsonl")
        if not os.path.exists(jpath):
            return fail(f"no journal at {jpath}")
        records, torn = journal.load_records(jpath)
        if torn is not None:
            return fail(f"journal has a torn line at {torn} even "
                        "after adopter recovery")
        kinds = [r.get("kind") for r in records]
        for want in ("create", "rule", "link", "restore", "digest"):
            if want not in kinds:
                return fail(f"journal never recorded a {want!r} "
                            f"event (kinds: {sorted(set(kinds))})")
        digests = sum(1 for k in kinds if k == "digest")
        print(f"journal-smoke: journal holds {len(records)} records, "
              f"{digests} digests, kinds {sorted(set(kinds))}",
              flush=True)

        # Deterministic replay: every digest bit-identical, rc 0.
        audit = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "replay_audit.py"),
             jpath, "--ckpt", ckpt_root,
             "--dump", os.path.join(tmpdir, "divergence")],
            capture_output=True, text=True, timeout=600)
        sys.stdout.write(audit.stdout)
        sys.stderr.write(audit.stderr)
        if audit.returncode != 0:
            return fail(f"replay_audit exited {audit.returncode}")
        print(f"journal-smoke: replay bit-identical through SetRule + "
              f"failover at turn {TARGET}", flush=True)
        print("journal-smoke: PASS", flush=True)
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(10)
        router.shutdown()


if __name__ == "__main__":
    rc = main()
    # os._exit dodges the known XLA daemon-thread teardown abort;
    # every gate already flushed its verdict.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
