"""fuse-smoke: prove the temporal-fusion tier end to end, fast.

Runs a reduced `bench.py --fuse` matrix IN-PROCESS on CPU (k ∈ {1, 4},
one 512² dense board, one 2-way mesh leg), then validates every surface
the fused tier is supposed to light up:

  * every emitted leg parses and is parity-clean — each k is
    bit-identical to the k=1 torus replay by construction of the gate;
  * the analytic halo observables obey the physics: exchange ROUNDS
    per turn at k=4 are exactly 1/4 (one exchange per macro-step) while
    BYTES per turn are conserved across k (a k-deep exchange ships
    2k rows per k turns — fusion cannot reduce bytes, only latency
    exposure, and a smoke that "showed" shrinking bytes would be
    measuring a bug);
  * gol_fused_dispatches_total{tier="mesh"} and the per-turn halo
    gauges hold real samples in the registry after the run;
  * tools/perf_compare.py round-trips the captured lines against the
    committed BASELINE.json entries (the same gate `make perf-gate`
    runs on full bench artifacts).

Exit 0 = pass.

    make fuse-smoke     # part of the `make smoke` chain
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
from contextlib import redirect_stdout

# Runnable as `python tools/fuse_smoke.py` from a bare clone: put the
# repo root (this file's parent's parent) ahead of tools/ on sys.path.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The mesh legs need devices; force 8 virtual host devices strictly
# before any jax backend initialisation (same guard as bench.py --fuse).
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

FUSE_SMOKE_KS = (1, 4)
FUSE_SMOKE_SIZE = 512
FUSE_SMOKE_TURNS = 256
FUSE_SMOKE_WAYS = (2,)
FUSE_SMOKE_MESH_TURNS = 256


def main() -> int:
    import bench

    problems = []
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.bench_fuse(ks=FUSE_SMOKE_KS,
                              sizes=(FUSE_SMOKE_SIZE,),
                              turns_override=FUSE_SMOKE_TURNS,
                              ways=FUSE_SMOKE_WAYS,
                              mesh_turns=FUSE_SMOKE_MESH_TURNS)
    captured = buf.getvalue()
    sys.stdout.write(captured)
    if rc != 0:
        problems.append(f"bench_fuse rc={rc} (parity gate failed?)")

    # ---- bench lines ---------------------------------------------------
    recs = []
    for line in captured.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            recs.append(json.loads(line))
        except ValueError:
            problems.append(f"unparseable bench line: {line[:80]!r}")
    by_name = {r.get("metric", ""): r for r in recs}
    n = FUSE_SMOKE_SIZE
    for needed in (
            f"cell-updates/sec (fused, k=1, {n}x{n})",
            f"cell-updates/sec (fused, k=4, {n}x{n})",
            "cell-updates/sec (fused, k=1, 1024x1024 2-way)",
            "cell-updates/sec (fused, k=4, 1024x1024 2-way)",
            "halo exchanges/turn (fused, k=4, 2-way)",
            "halo bytes/turn (fused, k=1, 2-way)",
            "halo bytes/turn (fused, k=4, 2-way)"):
        if needed not in by_name:
            problems.append(f"missing bench line {needed!r}")
    for r in recs:
        if r.get("detail", {}).get("alive_parity") is not True:
            problems.append(f"parity not clean on {r.get('metric')!r}")

    # ---- the physics the gate encodes ----------------------------------
    ex4 = by_name.get("halo exchanges/turn (fused, k=4, 2-way)", {})
    if ex4 and ex4.get("value") != 0.25:
        problems.append(f"k=4 exchange rounds/turn should be exactly "
                        f"1/4, got {ex4.get('value')!r}")
    b1 = by_name.get("halo bytes/turn (fused, k=1, 2-way)", {})
    b4 = by_name.get("halo bytes/turn (fused, k=4, 2-way)", {})
    if b1 and b4 and b1.get("value") != b4.get("value"):
        problems.append(f"halo bytes/turn must be CONSERVED across k "
                        f"(got k=1 {b1.get('value')!r} vs k=4 "
                        f"{b4.get('value')!r})")

    # ---- registry families hold real samples ---------------------------
    from gol_tpu.obs.metrics import REGISTRY

    samples = {}
    for line in REGISTRY.render_prometheus().splitlines():
        if line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        try:
            samples[key] = float(val)
        except ValueError:
            pass
    for key in ('gol_fused_dispatches_total{tier="mesh"}',
                'gol_halo_exchanges_per_turn{axis="rows"}',
                'gol_halo_bytes_per_turn{axis="rows"}'):
        if samples.get(key, 0) <= 0:
            problems.append(f"registry sample not populated: {key!r} "
                            f"= {samples.get(key)}")

    # ---- perf_compare gate round-trip ----------------------------------
    import perf_compare

    tmpdir = tempfile.mkdtemp(prefix="gol_fuse_smoke_")
    out_path = os.path.join(tmpdir, "fuse.jsonl")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(captured)
    if perf_compare.main([os.path.join(_ROOT, "BASELINE.json"),
                          out_path]) != 0:
        problems.append("perf_compare gate failed on the fused legs")

    if problems:
        for p in problems:
            print(f"fuse-smoke: FAIL: {p}", file=sys.stderr)
        return 1
    legs = len(recs)
    disp = int(samples.get('gol_fused_dispatches_total{tier="mesh"}',
                           0))
    print(f"fuse-smoke: OK — {legs} gated fused line(s), every k "
          f"bit-identical to the k=1 replay, {disp} fused mesh "
          f"dispatch(es) metered, bytes/turn conserved across k")
    return 0


if __name__ == "__main__":
    sys.exit(main())
