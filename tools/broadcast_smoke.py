"""broadcast-smoke: end-to-end checks for the broadcast fan-out tier.

One in-process fleet server, one continuously-advancing run, three
Subscribe viewers — two live decoders and one deliberately stalled
socket — driving every invariant the tier promises:

  * encode-once: over a measured window, gol_wire_encode_calls_total
    advances EXACTLY as much as gol_bcast_frames_total — one encode
    per published frame no matter how many subscribers it fans out to;
  * shared bytes: both live viewers decode bit-identical boards at
    every common turn (same wire frames, not per-viewer renders);
  * slow-subscriber policy: the stalled viewer is skipped ahead to a
    keyframe (gol_bcast_frames_dropped_total ticks) while the live
    viewer and the engine's chunk loop never notice;
  * DestroyRun: every `run_id|vkey` entry leaves the server's view
    basis cache and subscribers get the end sentinel, not a hang;
  * gateway sockets carry TCP_NODELAY + SO_KEEPALIVE, and the obs
    registry exposes the tier's metric families.

Exit 0 = every PASS line printed; nonzero on the first failure class.
Wired into `make broadcast-smoke` (and the `make smoke` chain) after
the gated `bench.py --broadcast` leg.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Fast frames + a tiny ring so the stalled viewer falls behind the
# ring head (and therefore skips) within a second, not a minute.
os.environ["GOL_BCAST_KEYFRAME"] = "4"
os.environ["GOL_BCAST_RING"] = "8"
os.environ["GOL_BCAST_HZ"] = "50"

import numpy as np  # noqa: E402

BOARD = 64
VIEW_CELLS = BOARD * BOARD


def _fail(msg: str) -> int:
    print(f"broadcast-smoke: FAIL: {msg}", file=sys.stderr)
    return 1


def _bcast_frames(obs) -> float:
    return sum(ch.value for ch in obs.BCAST_FRAMES.children().values())


def main() -> int:
    from gol_tpu.client import RemoteEngine
    from gol_tpu.fleet import FleetEngine
    from gol_tpu.obs import REGISTRY
    from gol_tpu.obs import catalog as obs
    from gol_tpu.server import EngineServer

    eng = FleetEngine(bucket_sizes=(BOARD,), chunk_turns=2, slot_base=8)
    srv = EngineServer(port=0, host="127.0.0.1", engine=eng)
    srv.start_background()
    address = f"127.0.0.1:{srv.port}"
    rc = 0
    live = []          # [(sub, {"turns": {turn: pixels}, "max": int})]
    stalled = None
    lock = threading.Lock()

    def _reader(sub, state):
        # recv() (not frames()) so the end-of-stream ConnectionError —
        # which carries the server's DestroyRun reason — is observable.
        try:
            while True:
                view, turn, _geom, header = sub.recv(timeout=20.0)
                with lock:
                    if len(state["turns"]) < 256:
                        state["turns"][turn] = view.copy()
                    state["max"] = max(state["max"], turn)
                    state["frames"] += 1
        except Exception as e:  # noqa: BLE001 — checked via state
            state["error"] = f"{type(e).__name__}: {e}"
        state["done"] = True

    try:
        ctl = RemoteEngine(address, timeout=20.0)
        rid = ctl.create_run(BOARD, BOARD)["run_id"]
        bound = ctl.attach_run(rid)

        threads = []
        for _ in range(2):
            sub = bound.subscribe(VIEW_CELLS, timeout=20.0)
            state = {"turns": {}, "max": -1, "frames": 0}
            th = threading.Thread(target=_reader, args=(sub, state),
                                  daemon=True)
            th.start()
            live.append((sub, state))
            threads.append(th)

        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            with lock:
                if all(s["frames"] >= 3 for _, s in live):
                    break
            time.sleep(0.05)
        else:
            return _fail("live viewers never warmed: "
                         f"{[dict(s, turns=len(s['turns'])) for _, s in live]}")

        # ---- stalled viewer: subscribe, then never read ----
        stalled = bound.subscribe(VIEW_CELLS, timeout=20.0)
        try:  # shrink both buffer sides so the stall bites fast
            stalled._sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_RCVBUF, 4096)
        except OSError:
            pass
        time.sleep(0.3)  # let the gateway admit it
        hub, gateway = srv._bcast
        for gsub in list(gateway._subs.values()):
            try:
                gsub.sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_SNDBUF, 4096)
            except OSError:
                pass

        # ---- gateway socket options (satellite: keepalive/nodelay) ----
        opts_ok = True
        for gsub in list(gateway._subs.values()):
            nd = gsub.sock.getsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY)
            ka = gsub.sock.getsockopt(socket.SOL_SOCKET,
                                      socket.SO_KEEPALIVE)
            if not nd or not ka:
                opts_ok = False
        if not opts_ok or not gateway._subs:
            rc |= _fail("adopted sockets missing TCP_NODELAY/"
                        "SO_KEEPALIVE")
        else:
            print(f"broadcast-smoke: PASS — {len(gateway._subs)} "
                  "adopted socket(s) carry TCP_NODELAY + SO_KEEPALIVE")

        # ---- encode-once window ----
        e0 = obs.WIRE_ENCODE_CALLS.value
        f0 = _bcast_frames(obs)
        d0 = obs.BCAST_FRAMES_DROPPED.value
        with lock:
            live_before = live[0][1]["max"]
        time.sleep(1.5)
        e1 = obs.WIRE_ENCODE_CALLS.value
        f1 = _bcast_frames(obs)
        frames = f1 - f0
        encodes = e1 - e0
        if frames <= 0 or encodes != frames:
            rc |= _fail(f"encode-once broken: {encodes} encode calls "
                        f"for {frames} published frames")
        else:
            print(f"broadcast-smoke: PASS — encode-once: {int(frames)} "
                  f"frames published, {int(encodes)} encode calls, "
                  f"3 subscribers")
        with lock:
            live_after = live[0][1]["max"]
        if live_after <= live_before:
            rc |= _fail("live viewer starved while a subscriber was "
                        f"stalled (turn {live_before} -> {live_after})")
        else:
            print("broadcast-smoke: PASS — live viewer + chunk loop "
                  f"unaffected by the stall (turn {live_before} -> "
                  f"{live_after})")

        # ---- drain the stalled viewer: expect a skip to a keyframe ----
        drops = 0.0
        resynced = False
        drain_deadline = time.monotonic() + 15.0
        last_turn = -1
        while time.monotonic() < drain_deadline:
            view, turn, _geom, header = stalled.recv(timeout=5.0)
            drops = obs.BCAST_FRAMES_DROPPED.value - d0
            if drops > 0 and header.get("key") and turn > last_turn:
                resynced = True
                break
            last_turn = max(last_turn, turn)
        if not resynced or drops <= 0:
            rc |= _fail(f"stalled viewer never resynced: drops={drops} "
                        f"resynced={resynced}")
        else:
            print("broadcast-smoke: PASS — stalled viewer skipped to a "
                  f"keyframe (turn {turn}), {int(drops)} frame sends "
                  "dropped and metered")

        # ---- shared-bytes parity between the two live viewers ----
        with lock:
            t0 = dict(live[0][1]["turns"])
            t1 = dict(live[1][1]["turns"])
        common = sorted(set(t0) & set(t1))
        if not common:
            rc |= _fail("live viewers share no common turns")
        else:
            bad = [t for t in common
                   if not np.array_equal(t0[t], t1[t])]
            if bad:
                rc |= _fail(f"shared-bytes parity broken at turns {bad[:4]}")
            else:
                print("broadcast-smoke: PASS — 2 live viewers decoded "
                      f"bit-identical boards at {len(common)} common "
                      "turns")

        # ---- DestroyRun: view-cache purge + stream end sentinel ----
        bound.get_view(VIEW_CELLS)  # prime the per-viewer basis cache
        with srv._view_cache_lock:
            primed = [k for k in srv._view_cache
                      if k.startswith(f"{rid}|")]
        if not primed:
            rc |= _fail("GetView did not prime a run-scoped view-cache "
                        "entry (smoke assumption broken)")
        ctl.destroy_run(rid)
        with srv._view_cache_lock:
            leaked = [k for k in srv._view_cache
                      if k.startswith(f"{rid}|")]
        if leaked:
            rc |= _fail(f"DestroyRun leaked view-cache entries {leaked}")
        else:
            print("broadcast-smoke: PASS — DestroyRun evicted all "
                  f"{len(primed)} run-scoped view-cache entries")
        end_deadline = time.monotonic() + 10.0
        while time.monotonic() < end_deadline:
            with lock:
                if all(s.get("done") for _, s in live):
                    break
            time.sleep(0.05)
        ends = [s.get("error", "") for _, s in live]
        if not all("destroyed" in e for e in ends):
            rc |= _fail(f"live viewers missed the end sentinel: {ends}")
        else:
            print("broadcast-smoke: PASS — both live viewers received "
                  "the DestroyRun end sentinel")

        # ---- obs registry families ----
        text = REGISTRY.render_prometheus()
        missing = [f for f in ("gol_bcast_streams",
                               "gol_bcast_subscribers",
                               "gol_gateway_connections",
                               "gol_bcast_frames_total",
                               "gol_bcast_frames_dropped_total",
                               "gol_bcast_sent_bytes_total",
                               "gol_bcast_fanout_ms")
                   if f"# TYPE {f} " not in text]
        if missing:
            rc |= _fail(f"registry missing families {missing}")
        else:
            print("broadcast-smoke: PASS — all 7 broadcast/gateway "
                  "metric families exposed")
    except Exception as e:  # noqa: BLE001 — smoke must exit nonzero
        rc |= _fail(f"unexpected {type(e).__name__}: {e}")
    finally:
        for sub, _ in live:
            try:
                sub.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if stalled is not None:
            try:
                stalled.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        eng.kill_prog()
        srv.shutdown()
    if rc == 0:
        print("broadcast-smoke: OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
