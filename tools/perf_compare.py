#!/usr/bin/env python3
"""Compare BENCH / run-report artifacts and gate on regressions.

    python tools/perf_compare.py BASELINE CANDIDATE [MORE...] [options]

The FIRST file is the baseline; every later file is compared against
it metric-by-metric. Accepted formats (auto-detected per file, no
flags needed — these are every perf artifact this repo produces):

  * bench.py stdout — one JSON object per line:
      {"metric", "value", "unit", "vs_baseline", "detail"}
  * driver BENCH_r0N.json — {"n", "cmd", "rc", "tail": "<those same
      lines as one string>", "parsed": <last line>}
  * gol-run-report/1 JSON-lines — `bench_leg` records carry
      metric/value/unit; plain engine reports contribute derived
      metrics (cups / turns_per_s medians over untraced chunks)
  * BASELINE.json — committed gate anchor: {"published":
      {metric: value | {"value": ..., "unit": ...}}}

Delta semantics: rate metrics (unit ending "/s", or "/sec" in the
name) are higher-is-better; "seconds"/"s"/"us"/"ms"-unit metrics,
overhead/latency-named metrics, and percentile-named metrics (a
p50/p95/p99 token or a trailing ms/us suffix in the name) are
lower-is-better. Deltas inside the noise floor (default 5%) are
reported but never gate. A regression beyond --max-regression
(default 10%) on any GATED metric (those matching --gate-pattern,
default "cell-updates|turns/sec|cups|snapshot MB/s|chunk_overhead_us|
rpc p\\d+ ms") fails the run.

Baseline-integrity audit (PR 6): when the baseline file is a
BASELINE.json, the tool also diffs it against its previous git
revision (or --baseline-prev FILE) and prints a `baseline_lowered`
table of every gated metric the committed anchor got WORSE at. A
lowered entry must carry an explicit `"waiver"` string that appears
in CHANGES.md — the r05 refresh silently normalized a 4.6x 512²
full-stack regression away, and this rule makes that impossible to
repeat: an unwaivered lowering fails the gate. --no-baseline-audit
skips the audit (artifact-vs-artifact comparisons of historical
files).

Exit codes: 0 = no gated regression; 1 = gated regression or
unwaivered baseline lowering; 2 = usage / no comparable metric
overlap.

`make perf-gate` runs this against the committed BASELINE.json.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import statistics
import sys
from typing import Dict, Optional, Tuple

# metric -> (value, unit-or-None)
Metrics = Dict[str, Tuple[float, Optional[str]]]

DEFAULT_NOISE_FLOOR = 5.0
DEFAULT_MAX_REGRESSION = 10.0
DEFAULT_GATE_PATTERN = (
    r"cell-updates|turns/sec|cups|snapshot MB/s|chunk_overhead_us"
    r"|rpc p\d+ ms|efficiency_pct|fleet_scaling_efficiency_pct"
    r"|overlap_pct|availability_pct|retries_per_call"
    r"|downtime_p\d+_ms|migration_downtime_p\d+_ms"
    r"|router_overhead_p\d+_ms"
    r"|halo (?:bytes|exchanges)/turn"
    r"|encode_calls_per_published_frame|viewer_fanout_p\d+_ms"
    r"|telemetry_overhead_pct|heartbeat_payload_p\d+_bytes"
    r"|alert_detection_p\d+_ms|journal_overhead_pct"
    r"|usage_overhead_pct|usage_attribution_error_pct"
    r"|conv_autoselect_win_pct")
DEFAULT_CHANGES_PATH = "CHANGES.md"


def _add(metrics: Metrics, metric, value, unit=None) -> None:
    try:
        value = float(value)
    except (TypeError, ValueError):
        return
    metrics[str(metric)] = (value, unit)


def _from_bench_lines(text: str, metrics: Metrics) -> None:
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            _add(metrics, rec["metric"], rec["value"], rec.get("unit"))


def _from_run_report(records, metrics: Metrics) -> None:
    cups, rates = [], []
    for rec in records:
        event = rec.get("event")
        if event == "bench_leg" and "metric" in rec:
            _add(metrics, rec["metric"], rec.get("value"),
                 rec.get("unit"))
        elif event == "chunk":
            if rec.get("cups"):
                cups.append(float(rec["cups"]))
            if rec.get("turns_per_s"):
                rates.append(float(rec["turns_per_s"]))
    # Engine-report derived metrics: medians over untraced chunks (the
    # report schema already excludes traced chunks from these fields).
    if cups:
        _add(metrics, "engine median cups", statistics.median(cups),
             "cell-updates/s")
    if rates:
        _add(metrics, "engine median turns/sec",
             statistics.median(rates), "turns/s")


def load_metrics(path: str) -> Metrics:
    """Parse one artifact into {metric: (value, unit)}."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    metrics: Metrics = {}
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if isinstance(doc, dict) and "published" in doc:  # BASELINE.json
        for metric, val in (doc.get("published") or {}).items():
            if isinstance(val, dict):
                _add(metrics, metric, val.get("value"), val.get("unit"))
            else:
                _add(metrics, metric, val)
        return metrics
    if isinstance(doc, dict) and "tail" in doc:  # driver BENCH_r0N.json
        _from_bench_lines(str(doc.get("tail") or ""), metrics)
        return metrics
    # JSON-lines: a run report (schema field) or raw bench stdout.
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            continue
    if any(str(r.get("schema", "")).startswith("gol-run-report")
           for r in records):
        _from_run_report(records, metrics)
        # bench_leg-free reports may still carry bench-format lines
        # (a concatenated artifact); fall through only if empty.
        if metrics:
            return metrics
    _from_bench_lines(text, metrics)
    return metrics


def _higher_is_better(metric: str, unit: Optional[str]) -> bool:
    # Scaling-quality percentages (the --mesh legs): explicit rule
    # FIRST — their unit is "%", which none of the heuristics below
    # classify, and "overlap" must not fall into any cost bucket.
    low0 = metric.lower()
    if "_efficiency_pct" in low0 or "_overlap_pct" in low0:
        return True
    # Chaos availability legs: availability is a FLOOR (higher is
    # better), retry spend is a CEILING (lower is better) — both are
    # unitless-ish quantities none of the later heuristics classify.
    if "availability" in low0:
        return True
    if "retries" in low0:
        return False
    # Attribution-error gates (the --usage leg): an error percentage
    # is a pure COST — its unit "%" hits no heuristic below and the
    # name carries no overhead/latency token, so without this rule it
    # would default to higher-is-better and the gate would reward a
    # meter that stops conserving.
    if "_error_pct" in low0 or "error_pct" in low0:
        return False
    # Broadcast-tier zero-work witness: encodes per published frame is
    # a flat COST gate (exactly 1.0 when the fan-out tier shares one
    # encode across every subscriber) — its unit "calls/frame" hits no
    # heuristic below and would default to higher-is-better, rewarding
    # the per-viewer re-encode the gate exists to forbid.
    if "encode_calls" in low0:
        return False
    # Temporal-fusion halo observables (the --fuse mesh legs): both are
    # per-advanced-turn COSTS — exchanges/turn is the latency-exposure
    # count fusion divides by k, bytes/turn is conserved (flat) — and
    # neither unit ("exchanges/turn", "bytes/turn") hits any heuristic
    # below, which would default them to higher-is-better and reward
    # the exact regression the fused gate exists to catch.
    if "bytes/turn" in low0 or "exchanges/turn" in low0:
        return False
    if unit and (unit.endswith("/s") or unit.endswith("/sec")):
        return True
    if "/sec" in metric or "/s " in metric or "cups" in metric.lower():
        return True
    if unit in ("s", "seconds", "ms", "us", "µs") or "seconds" in metric:
        return False
    # Cost-flavoured names: chunk_overhead_us, p99 latency, … — without
    # this, a time-denominated gated metric would default to higher-is-
    # better and the gate would reward the regression it exists to catch.
    low = metric.lower()
    if "overhead" in low or "latency" in low:
        return False
    # Percentile / time-suffixed names (the PR 8 load-leg metrics are
    # "rpc p50 ms (load, CreateRun)"-shaped): a pXX token or a trailing
    # ms/us/s suffix marks a latency quantity — lower is better even
    # when the unit field went missing in transit.
    if re.search(r"(^|[^a-z0-9])p(50|90|95|99)([^a-z0-9]|$)", low):
        return False
    if low.endswith("_ms") or low.endswith("_us") or low.endswith(" ms"):
        return False
    return True  # throughput-flavoured by default


# ------------------------------------------------- baseline integrity

def parse_baseline_doc(text: str):
    """BASELINE.json text → ({metric: (value, unit)}, {metric: waiver}).
    Returns (None, None) when the text is not a BASELINE document."""
    try:
        doc = json.loads(text)
    except ValueError:
        return None, None
    if not isinstance(doc, dict) or "published" not in doc:
        return None, None
    metrics: Metrics = {}
    waivers: Dict[str, str] = {}
    for metric, val in (doc.get("published") or {}).items():
        if isinstance(val, dict):
            _add(metrics, metric, val.get("value"), val.get("unit"))
            w = val.get("waiver")
            if isinstance(w, str) and w.strip():
                waivers[str(metric)] = w.strip()
        else:
            _add(metrics, metric, val)
    return metrics, waivers


def _git_prev_text(path: str) -> Optional[str]:
    """The most recent committed revision of `path` whose content
    differs from the working copy — the anchor the current baseline is
    an UPDATE of. None when git/history is unavailable (fresh clone
    without the file, shallow history, not a repo)."""
    import subprocess

    d = os.path.dirname(os.path.abspath(path)) or "."
    name = os.path.basename(path)
    try:
        with open(path, "r", encoding="utf-8") as f:
            cur = f.read()
        revs = subprocess.run(
            ["git", "log", "-n", "16", "--format=%H", "--", name],
            cwd=d, capture_output=True, text=True, timeout=30)
        for rev in revs.stdout.split():
            # ":./name" resolves relative to cwd, not the repo root.
            out = subprocess.run(
                ["git", "show", f"{rev}:./{name}"],
                cwd=d, capture_output=True, text=True, timeout=30)
            if out.returncode == 0 and out.stdout \
                    and out.stdout != cur:
                return out.stdout
    except Exception:
        return None
    return None


def audit_baseline(cur_text: str, prev_text: str, gate_re,
                   changes_text: Optional[str]) -> Optional[list]:
    """Diff two BASELINE.json revisions: one row per GATED metric the
    current revision is WORSE at than the previous. Each lowering must
    be waived — an explicit `"waiver"` string on the entry that also
    appears in CHANGES.md (when readable), so every normalized
    regression leaves a reviewable paper trail. Returns None when
    either text is not a BASELINE document."""
    cur, waivers = parse_baseline_doc(cur_text)
    prev, _ = parse_baseline_doc(prev_text)
    if cur is None or prev is None:
        return None
    rows = []
    for metric in sorted(prev):
        if not gate_re.search(metric):
            continue
        prev_v, prev_u = prev[metric]
        if metric not in cur:
            # A REMOVED gated entry is the stealthiest lowering of all
            # (deleting the anchor un-gates the metric entirely), so it
            # gets the same treatment as a lowered one. The entry is
            # gone and cannot carry a waiver, so the paper trail moves
            # whole to CHANGES.md: the exact metric name must appear
            # there.
            ok = bool(changes_text is not None
                      and metric in changes_text)
            rows.append({
                "metric": metric, "unit": prev_u,
                "previous": prev_v, "current": None,
                "delta_pct": None, "waiver": None, "ok": ok,
                "problem": None if ok else
                "removed from baseline (name not in CHANGES.md)",
            })
            continue
        cur_v, cur_u = cur[metric]
        hib = _higher_is_better(metric, cur_u or prev_u)
        if (cur_v >= prev_v) if hib else (cur_v <= prev_v):
            continue  # unchanged or raised — no integrity question
        waiver = waivers.get(metric)
        if not waiver:
            problem = "no waiver"
        elif changes_text is not None and waiver not in changes_text:
            problem = "waiver not found in CHANGES.md"
        else:
            problem = None
        rows.append({
            "metric": metric, "unit": cur_u or prev_u,
            "previous": prev_v, "current": cur_v,
            "delta_pct": round(
                (cur_v - prev_v) / abs(prev_v) * 100.0, 2)
            if prev_v else None,
            "waiver": waiver, "ok": problem is None,
            "problem": problem,
        })
    return rows


def compare(baseline: Metrics, candidate: Metrics,
            noise_floor: float, max_regression: float,
            gate_re) -> Tuple[list, int]:
    """Rows + worst gated regression pct for one candidate file."""
    rows = []
    worst = 0.0
    for metric in sorted(baseline):
        if metric not in candidate:
            continue
        base_v, base_u = baseline[metric]
        cand_v, cand_u = candidate[metric]
        unit = cand_u or base_u
        if base_v == 0:
            continue
        hib = _higher_is_better(metric, unit)
        delta_pct = (cand_v - base_v) / abs(base_v) * 100.0
        # regression_pct: how far the candidate moved in the BAD
        # direction, as a positive number.
        regression_pct = -delta_pct if hib else delta_pct
        gated = bool(gate_re.search(metric))
        verdict = "ok"
        if abs(delta_pct) < noise_floor:
            verdict = "noise"
        elif regression_pct > 0:
            verdict = "regression"
        else:
            verdict = "improvement"
        fails = (gated and verdict == "regression"
                 and regression_pct > max_regression)
        if fails:
            verdict = "FAIL"
            worst = max(worst, regression_pct)
        rows.append({
            "metric": metric, "unit": unit,
            "baseline": base_v, "candidate": cand_v,
            "delta_pct": round(delta_pct, 2),
            "higher_is_better": hib, "gated": gated,
            "verdict": verdict,
        })
    return rows, worst


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare BENCH/run-report artifacts; gate on "
                    "regressions (first file = baseline)")
    ap.add_argument("files", nargs="+", metavar="FILE",
                    help="baseline first, then one or more candidates")
    ap.add_argument("--noise-floor", type=float,
                    default=DEFAULT_NOISE_FLOOR, metavar="PCT",
                    help="ignore deltas smaller than PCT%% (default 5)")
    ap.add_argument("--max-regression", type=float,
                    default=DEFAULT_MAX_REGRESSION, metavar="PCT",
                    help="fail on gated metrics regressing more than "
                         "PCT%% (default 10)")
    ap.add_argument("--gate-pattern", default=DEFAULT_GATE_PATTERN,
                    metavar="REGEX",
                    help="metrics that can fail the gate (default "
                         "%(default)r); others are report-only")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object "
                         "instead of the table")
    ap.add_argument("--baseline-prev", metavar="FILE", default="",
                    help="previous BASELINE.json revision for the "
                         "integrity audit (default: most recent git "
                         "revision of the baseline file that differs "
                         "from it)")
    ap.add_argument("--no-baseline-audit", action="store_true",
                    help="skip the baseline-lowered integrity audit "
                         "(for comparing historical artifacts)")
    ap.add_argument("--changes", metavar="FILE",
                    default=DEFAULT_CHANGES_PATH,
                    help="CHANGES.md to validate waiver references "
                         "against (default %(default)s, resolved "
                         "relative to the baseline file)")
    args = ap.parse_args(argv)
    if len(args.files) < 2:
        ap.error("need a baseline and at least one candidate file")
    try:
        gate_re = re.compile(args.gate_pattern)
    except re.error as e:
        ap.error(f"bad --gate-pattern: {e}")

    try:
        baseline = load_metrics(args.files[0])
    except OSError as e:
        print(f"perf_compare: cannot read baseline: {e}",
              file=sys.stderr)
        return 2
    if not baseline:
        print(f"perf_compare: no metrics found in baseline "
              f"{args.files[0]}", file=sys.stderr)
        return 2

    failed = False
    any_overlap = False
    report = {"baseline": args.files[0], "candidates": []}

    # Baseline-integrity audit: only meaningful when the anchor itself
    # is a BASELINE.json (artifact-vs-artifact comparisons have no
    # committed anchor to audit).
    audit_rows = None
    if not args.no_baseline_audit:
        try:
            with open(args.files[0], "r", encoding="utf-8") as f:
                cur_text = f.read()
        except OSError:
            cur_text = ""
        cur_doc, _ = parse_baseline_doc(cur_text)
        prev_text = None
        if cur_doc is not None:
            if args.baseline_prev:
                try:
                    with open(args.baseline_prev, "r",
                              encoding="utf-8") as f:
                        prev_text = f.read()
                except OSError as e:
                    print(f"perf_compare: cannot read --baseline-prev: "
                          f"{e}", file=sys.stderr)
                    return 2
            else:
                prev_text = _git_prev_text(args.files[0])
        if prev_text is not None:
            changes_path = args.changes
            if not os.path.isabs(changes_path):
                changes_path = os.path.join(
                    os.path.dirname(os.path.abspath(args.files[0])),
                    changes_path)
            changes_text = None
            try:
                with open(changes_path, "r", encoding="utf-8") as f:
                    changes_text = f.read()
            except OSError:
                pass  # no CHANGES.md to check references against
            audit_rows = audit_baseline(cur_text, prev_text, gate_re,
                                        changes_text)
    if audit_rows:
        report["baseline_lowered"] = audit_rows
        if any(not r["ok"] for r in audit_rows):
            failed = True
        if not args.json:
            print("== baseline_lowered (committed anchor vs its "
                  "previous revision)")
            width = max(len(r["metric"]) for r in audit_rows)
            for r in audit_rows:
                if r["ok"]:
                    verdict = ("waived: " + r["waiver"] if r["waiver"]
                               else "removal noted in CHANGES.md")
                else:
                    verdict = "FAIL: " + r["problem"]
                cur_s = ("(removed)" if r["current"] is None
                         else f"{r['current']:.6g}")
                print(f"  {r['metric']:<{width}}  "
                      f"{r['previous']:>14.6g} -> "
                      f"{cur_s:>14}  "
                      f"{(r['delta_pct'] or 0):>+8.2f}%  {verdict}")
    for path in args.files[1:]:
        try:
            candidate = load_metrics(path)
        except OSError as e:
            print(f"perf_compare: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        rows, worst = compare(baseline, candidate, args.noise_floor,
                              args.max_regression, gate_re)
        if rows:
            any_overlap = True
        if worst > 0:
            failed = True
        report["candidates"].append(
            {"file": path, "rows": rows,
             "worst_gated_regression_pct": round(worst, 2)})
        if not args.json:
            print(f"== {os.path.basename(args.files[0])} -> "
                  f"{os.path.basename(path)}")
            if not rows:
                print("  (no comparable metrics)")
            width = max((len(r["metric"]) for r in rows), default=0)
            for r in rows:
                gate = "gated" if r["gated"] else "     "
                print(f"  {r['metric']:<{width}}  "
                      f"{r['baseline']:>14.6g} -> "
                      f"{r['candidate']:>14.6g}  "
                      f"{r['delta_pct']:>+8.2f}%  {gate}  "
                      f"{r['verdict']}")
    if not any_overlap:
        print("perf_compare: no metric overlap between baseline and "
              "any candidate", file=sys.stderr)
        return 2
    report["ok"] = not failed
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    elif failed:
        if audit_rows and any(not r["ok"] for r in audit_rows):
            print("perf-gate: FAIL (baseline lowered a gated metric "
                  "without a CHANGES.md-referenced waiver)")
        else:
            print("perf-gate: FAIL (regression beyond "
                  f"{args.max_regression:g}% on a gated metric)")
    else:
        print("perf-gate: ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
