#!/usr/bin/env python3
"""Deterministic replay auditor for gol-journal/1 black boxes.

Feeds a recorded journal (one file, or an ordered lineage of segment
files for a run that crossed members) into a fresh engine and asserts
bit-identical board digests at EVERY digest event:

  1. chain verification first — a flipped bit, removed line, reordered
     pair, or truncated tail is reported at the exact offending seq
     (tools never replay a tampered history);
  2. forward replay — the seed is reconstructed from the create event
     (inline board, or the deterministic run_id-keyed soup), rule
     changes apply at their recorded turns, link/restore events rewind
     to their recorded turn, and each digest event's board_sha256 is
     recomputed from the replayed board with the same canonical payload
     hashing checkpoint manifests use;
  3. on mismatch the auditor bisects to the first divergent digest (the
     tightest bracket the recorded digests allow), dumps the replayed
     board, the expected board recovered from a matching checkpoint
     when --ckpt is given, and a flight record, then exits nonzero and
     increments gol_replay_divergence_total.

Exit codes: 0 verified, 1 divergence, 2 chain verification failure,
3 unusable input (missing file, unreplayable seed with digests to
check, unsupported representation).

Usage:
  python tools/replay_audit.py JOURNAL.jsonl [SEGMENT2.jsonl ...]
      [--expect-head HEX] [--expect-seq N] [--ckpt DIR] [--dump DIR]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gol_tpu import journal  # noqa: E402
from gol_tpu.models import parse_rule  # noqa: E402

# Fixed advance quantum: packed_run_turns jits per turn count, so
# replay steps in one compiled chunk shape plus one remainder shape.
CHUNK = 256
# Rewind anchors kept besides the seed (link/restore recompute from the
# nearest earlier anchor — bounded so a long journal stays O(1) memory).
CACHE_BOARDS = 32


def _step_np(board01: np.ndarray, turns: int, rule) -> np.ndarray:
    """Pure-numpy torus stepper for boards whose width is not
    word-aligned (the fleet checkpoints those as u8). Integer-exact —
    any correct evolution of the same torus is bit-identical."""
    born = np.array([1 if n in rule.born else 0 for n in range(9)],
                    dtype=np.uint8)
    surv = np.array([1 if n in rule.survive else 0 for n in range(9)],
                    dtype=np.uint8)
    b = board01.astype(np.uint8)
    for _ in range(turns):
        n = sum(np.roll(np.roll(b, dy, 0), dx, 1)
                for dy in (-1, 0, 1) for dx in (-1, 0, 1)
                if (dy, dx) != (0, 0))
        b = np.where(b != 0, surv[n], born[n]).astype(np.uint8)
    return b


def _step_packed(board01: np.ndarray, turns: int, rule) -> np.ndarray:
    import jax

    from gol_tpu.fleet.buckets import board_to_words, words_to_board
    from gol_tpu.ops.bitpack import packed_run_turns

    h, w = board01.shape
    words = board_to_words(board01)
    while turns >= CHUNK:
        words = packed_run_turns(words, CHUNK, rule)
        turns -= CHUNK
    if turns:
        words = packed_run_turns(words, turns, rule)
    return words_to_board(np.asarray(jax.device_get(words)), h, w)


class Replayer:
    """Forward-replays one run's journal records, checking digests."""

    def __init__(self, dump_dir: str = "", ckpt_root: str = ""):
        self.dump_dir = dump_dir
        self.ckpt_root = ckpt_root
        self.board: Optional[np.ndarray] = None
        self.turn = 0
        self.rule = None
        self.run_id = ""
        self.unreplayable: Optional[str] = None
        self.checked = 0
        self.skipped = 0
        self.last_good: Optional[Tuple[int, int]] = None  # (seq, turn)
        self._cache: "dict[int, np.ndarray]" = {}

    # ------------------------------------------------------------ state

    def _remember(self, turn: int) -> None:
        self._cache[turn] = self.board.copy()
        if len(self._cache) > CACHE_BOARDS + 1:
            # Keep the oldest anchor (the seed) and the newest rest.
            evict = sorted(self._cache)[1]
            del self._cache[evict]

    def _advance(self, to_turn: int) -> None:
        n = to_turn - self.turn
        if n < 0:
            raise journal.JournalError(
                f"cannot advance backwards {self.turn} -> {to_turn}")
        if n == 0:
            return
        if self.board.shape[1] % 32 == 0:
            self.board = _step_packed(self.board, n, self.rule)
        else:
            self.board = _step_np(self.board, n, self.rule)
        self.turn = to_turn

    def _rewind_to(self, to_turn: int) -> None:
        if to_turn >= self.turn:
            self._advance(to_turn)
            return
        anchors = [t for t in self._cache if t <= to_turn]
        if not anchors:
            raise journal.JournalError(
                f"no replay anchor at or before turn {to_turn} "
                f"(earliest cached: {min(self._cache, default='none')})")
        t0 = max(anchors)
        self.board = self._cache[t0].copy()
        self.turn = t0
        self._advance(to_turn)

    def _board_sha(self, repr_: str) -> str:
        if repr_ == "packed":
            from gol_tpu.fleet.buckets import board_to_words

            words = np.ascontiguousarray(board_to_words(self.board))
            return journal.board_digest(words, "packed")
        if repr_ == "u8":
            return journal.board_digest(self.board, "u8")
        raise journal.JournalError(
            f"unsupported digest representation {repr_!r}")

    # ----------------------------------------------------------- events

    def _seed_board(self, rec: dict) -> Optional[np.ndarray]:
        if isinstance(rec.get("seed"), dict):
            return journal.decode_board(rec["seed"])
        if rec.get("seed_kind") == "soup":
            from gol_tpu.fleet.engine import _soup

            return _soup(str(rec.get("run_id", self.run_id)),
                         int(rec["h"]), int(rec["w"]))
        return None

    def _apply_create(self, rec: dict) -> None:
        self.run_id = str(rec.get("run_id", ""))
        self.rule = parse_rule(rec.get("rule") or "B3/S23")
        self.turn = int(rec.get("turn", 0))
        board = self._seed_board(rec)
        if board is None:
            self.unreplayable = (
                f"seed is external (seq {rec['seq']}): digest-only "
                "create events cannot reseed a replay")
            return
        self.board = board
        self._cache.clear()
        self._remember(self.turn)
        want = rec.get("board_sha256")
        if want and self._board_sha(rec.get("repr", "packed")) != want:
            raise journal.JournalError(
                f"seed digest mismatch at seq {rec['seq']}: the "
                "recorded seed does not hash to the recorded "
                "board_sha256")

    def apply(self, rec: dict) -> Optional[dict]:
        """Apply one record; returns a divergence report or None."""
        kind = rec.get("kind")
        if self.unreplayable is not None:
            if kind == "digest":
                self.skipped += 1
            return None
        if kind == "create":
            self._apply_create(rec)
            return None
        if self.board is None:
            # Records before the run's create (a pool digest racing
            # registration) have nothing to check against yet.
            if kind == "digest":
                self.skipped += 1
            return None
        if kind == "rule":
            self._advance(int(rec["turn"]))
            self.rule = parse_rule(rec["rule"])
        elif kind == "reseed":
            board = self._seed_board(rec)
            if board is None:
                self.unreplayable = (
                    f"reseed at seq {rec['seq']} is external "
                    "(digest-only)")
                return None
            self.board = board
            self.turn = int(rec.get("turn", self.turn))
            self._cache.clear()
            self._remember(self.turn)
        elif kind in ("link", "restore"):
            self._rewind_to(int(rec["turn"]))
            want = rec.get("board_sha256")
            if want:
                got = self._board_sha(rec.get("repr", "packed"))
                if got != want:
                    return self._diverged(rec, want, got)
                self.checked += 1
                self.last_good = (rec["seq"], self.turn)
                self._remember(self.turn)
        elif kind == "digest":
            self._rewind_to(int(rec["turn"]))
            want = rec.get("board_sha256")
            got = self._board_sha(rec.get("repr", "packed"))
            if got != want:
                return self._diverged(rec, want, got)
            self.checked += 1
            self.last_good = (rec["seq"], self.turn)
            self._remember(self.turn)
        # pause/resume/fuse/end/migrate_out carry no replayable state.
        return None

    # ------------------------------------------------------- divergence

    def _expected_board(self, turn: int) -> Optional[np.ndarray]:
        """Best-effort recovery of the ORIGINAL board at the divergent
        turn from a checkpoint root (the digest events at checkpoint
        cadence have a durable twin)."""
        if not self.ckpt_root:
            return None
        try:
            from gol_tpu.ckpt import manifest as mf
            from gol_tpu.ckpt import reshard as reshard_mod

            for d in (os.path.join(self.ckpt_root,
                                   f"run-{self.run_id}"),
                      self.ckpt_root):
                if not os.path.isdir(d):
                    continue
                for name in sorted(os.listdir(d)):
                    if not (name.startswith("ckpt-")
                            and name.endswith(".json")):
                        continue
                    path = os.path.join(d, name)
                    try:
                        m = mf.read_manifest(path)
                    except Exception:
                        continue
                    if int(m.get("turn", -1)) != turn:
                        continue
                    m = mf.verify_manifest(path)
                    can = reshard_mod.load_canonical(
                        mf.payload_path(path, m))
                    return reshard_mod.board01_of(can)
        except Exception:
            return None
        return None

    def _diverged(self, rec: dict, want: str, got: str) -> dict:
        report = {
            "run_id": self.run_id,
            "seq": rec.get("seq"),
            "turn": int(rec.get("turn", self.turn)),
            "expected_sha": want,
            "replayed_sha": got,
            "last_good_seq": (self.last_good or (None, None))[0],
            "last_good_turn": (self.last_good or (None, None))[1],
        }
        try:
            from gol_tpu.obs import catalog as obs

            obs.REPLAY_DIVERGENCE.inc()
        except Exception:
            pass
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                stem = os.path.join(
                    self.dump_dir,
                    f"divergence-{journal._safe_name(self.run_id)}"
                    f"-seq{rec.get('seq')}")
                np.savez_compressed(
                    stem + "-replayed.npz", board=self.board,
                    turn=self.turn)
                report["replayed_board"] = stem + "-replayed.npz"
                expected = self._expected_board(report["turn"])
                if expected is not None:
                    np.savez_compressed(
                        stem + "-expected.npz", board=expected,
                        turn=report["turn"])
                    report["expected_board"] = stem + "-expected.npz"
                with open(stem + ".json", "w", encoding="utf-8") as f:
                    json.dump(report, f, indent=2, sort_keys=True)
                    f.write("\n")
                from gol_tpu.obs import flight

                flight.FLIGHT.record_event(
                    {"level": "error", "event": "replay.divergence",
                     **{k: v for k, v in report.items()
                        if isinstance(v, (str, int, float))}})
                fpath = flight.FLIGHT.dump(
                    reason="replay-divergence",
                    path=stem + "-flight.json")
                if fpath:
                    report["flight_record"] = fpath
            except Exception as e:
                report["dump_error"] = f"{type(e).__name__}: {e}"
        return report

def _load_segments(paths: List[str]) -> Tuple[List[List[dict]],
                                              Optional[str]]:
    segments: List[List[dict]] = []
    for p in paths:
        records, torn = journal.load_records(p)
        if torn is not None:
            return segments, f"{p}: torn trailing record at line {torn}"
        segments.append(records)
    return segments, None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="verify + deterministically replay a gol-journal/1 "
                    "black box")
    ap.add_argument("segments", nargs="+", metavar="JOURNAL.jsonl",
                    help="journal file(s); multiple files form an "
                         "ordered lineage stitched across link events")
    ap.add_argument("--expect-head", default="",
                    help="expected final chain head (e.g. from the "
                         "newest checkpoint manifest's journal stamp) "
                         "— catches tail truncation")
    ap.add_argument("--expect-seq", type=int, default=None,
                    help="expected final seq (paired with "
                         "--expect-head)")
    ap.add_argument("--ckpt", default="",
                    help="checkpoint root for recovering the original "
                         "board at a divergent digest turn")
    ap.add_argument("--dump", default="",
                    help="directory for divergence artifacts (boards, "
                         "report, flight record)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    def say(msg: str) -> None:
        if not args.quiet:
            print(msg)

    try:
        segments, torn_err = _load_segments(args.segments)
    except (OSError, journal.JournalError) as e:
        print(f"replay_audit: {e}", file=sys.stderr)
        return 2
    if torn_err is not None:
        print(f"replay_audit: chain FAILED: {torn_err}",
              file=sys.stderr)
        return 2

    if len(segments) == 1:
        res = journal.verify_chain(
            segments[0],
            expected_head=args.expect_head or None,
            expected_seq=args.expect_seq)
    else:
        res = journal.verify_segments(segments)
        if res["ok"] and args.expect_head \
                and res["head"] != args.expect_head:
            res = dict(res, ok=False, bad_seq=res["last_seq"] + 1,
                       reason="truncated: final head does not match "
                              "--expect-head")
    if not res["ok"]:
        seg = f" segment {res['segment']}" if "segment" in res else ""
        print(f"replay_audit: chain FAILED at seq {res['bad_seq']}"
              f"{seg}: {res['reason']}", file=sys.stderr)
        return 2
    say(f"chain ok: {res['count']} records, head {res['head'][:16]}…, "
        f"last seq {res['last_seq']}")

    rp = Replayer(dump_dir=args.dump, ckpt_root=args.ckpt)
    for seg in segments:
        for rec in seg:
            try:
                report = rp.apply(rec)
            except journal.JournalError as e:
                print(f"replay_audit: replay FAILED at seq "
                      f"{rec.get('seq')}: {e}", file=sys.stderr)
                return 3
            if report is not None:
                print("replay_audit: DIVERGENCE at seq "
                      f"{report['seq']} turn {report['turn']}: "
                      f"expected {report['expected_sha'][:16]}…, "
                      f"replayed {report['replayed_sha'][:16]}… "
                      f"(last good digest: turn "
                      f"{report['last_good_turn']})", file=sys.stderr)
                for k in ("replayed_board", "expected_board",
                          "flight_record", "first_divergent_turn"):
                    if k in report:
                        print(f"  {k}: {report[k]}", file=sys.stderr)
                return 1
    if rp.unreplayable is not None:
        level = sys.stderr if rp.skipped else sys.stdout
        print(f"replay_audit: unreplayable: {rp.unreplayable} "
              f"({rp.skipped} digest(s) unchecked)", file=level)
        return 3 if rp.skipped else 0
    say(f"replay ok: {rp.checked} digest(s) bit-identical"
        + (f", {rp.skipped} skipped (pre-create)" if rp.skipped else "")
        + f", final turn {rp.turn}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
