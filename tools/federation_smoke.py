"""federation-smoke: prove the federated serving tier end to end on CPU.

One acceptance scenario (PR 12), real member processes behind a real
in-process router:

  * three `--fleet --federate` servers register with a FederationRouter
    and heartbeat; runs created THROUGH the router are HRW-placed over
    the live members and driven to a parked target turn with per-run
    manifests landing under one shared checkpoint root;
  * one member (the owner of at least one run) is SIGKILLed: the
    router's sweeper must declare it dead within GOL_FED_DEAD_AFTER,
    meter the failover, and re-home its runs onto survivors through
    AdoptRun -> FleetEngine.adopt_run (the PR-10 quarantine->restore
    machinery, reading the dead member's run-<id>/ manifests);
  * every run — adopted and undisturbed alike — must then be readable
    through the SAME router address, parked at the SAME target turn,
    bit-identical to a device torus replay of its seed;
  * the registry families (gol_fed_members{state},
    gol_fed_failovers_total) and the /healthz federation member table
    must reflect exactly one death.

Exit 0 = pass.

    make federation-smoke   # bench.py --federation + gate, then this

The member/router spawn helpers here are also imported by bench.py's
--federation leg (same pattern as tools/load_smoke.py).
"""

from __future__ import annotations

import os
import signal
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Tight failure-detection clock for tests/benches: sub-second beats, a
# ~1 s death verdict, and a reroute window generous enough to cover an
# adopting member's restore + recompile on a cold CPU host.
FED_ENV = {
    "GOL_FED_HEARTBEAT": "0.2",
    "GOL_FED_DEAD_AFTER": "1.2",
    "GOL_FED_REROUTE": "30",
}


def fail(msg: str) -> int:
    print(f"federation-smoke: FAIL — {msg}", flush=True)
    return 1


def expected_board01(seed01: np.ndarray, turns: int) -> np.ndarray:
    """{0,1} board after `turns` device torus turns — the parity
    oracle (same packed stencil the fleet runs on, single board)."""
    from gol_tpu.ops.bitpack import (
        pack_np, packed_run_turns, unpack_np, words_bytes_np)

    words = packed_run_turns(pack_np(seed01).view("<u4"), turns)
    h, w = seed01.shape
    return unpack_np(words_bytes_np(np.asarray(words)), h, w)


def spawn_member(tmpdir, ckpt_root: str, router_port: int,
                 ckpt_every: int = 4, extra_env=None):
    """One federated fleet-server subprocess (checkpoints under the
    SHARED root, heartbeating to the router). Returns the Popen; the
    caller reads the bound port with `wait_member`."""
    from tests.server_harness import spawn_server

    env = dict(FED_ENV)
    env.update(extra_env or {})
    return spawn_server(
        0, tmpdir, extra_env=env,
        extra_args=("--fleet", "--checkpoint", ckpt_root,
                    "--ckpt-every", str(ckpt_every),
                    "--federate", f"127.0.0.1:{router_port}"))


def wait_member(proc, timeout: float = 180.0):
    """The member's advertised address ("127.0.0.1:<port>") once its
    serving banner appears, or None."""
    from tests.server_harness import wait_port

    port = wait_port(proc, timeout=timeout)
    return f"127.0.0.1:{port}" if port else None


def wait_live(router, n: int, timeout: float = 60.0) -> bool:
    """True once the router's registry counts `n` live members."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if router.registry.members_doc().get("live", 0) >= n:
            return True
        time.sleep(0.1)
    return False


def wait_runs_at(cli, run_ids, turn: int, timeout: float = 300.0):
    """Poll ListRuns through the router until every id is present at
    >= `turn`; returns {run_id: member} or None on timeout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            runs, _ = cli.list_runs()
        except Exception:
            time.sleep(0.3)
            continue
        recs = {r["run_id"]: r for r in runs}
        if all(rid in recs and recs[rid]["turn"] >= turn
               for rid in run_ids):
            return {rid: recs[rid]["member"] for rid in run_ids}
        time.sleep(0.3)
    return None


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("GOL_CHAOS", None)
    os.environ.update(FED_ENV)

    from gol_tpu.client import RemoteEngine
    from gol_tpu.federation.router import FederationRouter
    from gol_tpu.obs import catalog as obs
    from gol_tpu.obs.http import healthz_doc

    tmpdir = tempfile.mkdtemp(prefix="gol_fed_smoke_")
    ckpt_root = os.path.join(tmpdir, "ck")
    n_members, n_runs, target = 3, 6, 32
    failovers0 = obs.FED_FAILOVERS.value

    router = FederationRouter(port=0).start_background()
    procs = [spawn_member(tmpdir, ckpt_root, router.port)
             for _ in range(n_members)]
    members = {}  # address -> proc
    try:
        for p in procs:
            addr = wait_member(p)
            if addr is None:
                return fail("a member never announced its port")
            members[addr] = p
        if not wait_live(router, n_members):
            return fail(f"registry never reached {n_members} live "
                        f"members: {router.registry.members_doc()}")
        print(f"federation-smoke: {n_members} members live behind "
              f"router :{router.port}", flush=True)

        cli = RemoteEngine(f"127.0.0.1:{router.port}", timeout=60.0)
        rng = np.random.default_rng(12)
        seeds = {}
        for i in range(n_runs):
            rid = f"fed{i}"
            board = (rng.random((64, 64)) < 0.3).astype(np.uint8)
            rec = cli.create_run(64, 64, board=board, run_id=rid,
                                 ckpt_every=4, target_turn=target)
            if rec["run_id"] != rid:
                return fail(f"CreateRun echoed {rec['run_id']}")
            seeds[rid] = board
        owners = wait_runs_at(cli, seeds, target)
        if owners is None:
            return fail("runs never parked at their target turn")
        spread = sorted(set(owners.values()))
        print(f"federation-smoke: {n_runs} runs parked at turn "
              f"{target} across {len(spread)} members", flush=True)

        # Kill the member that owns fed0 (guaranteed at least one run).
        victim = owners["fed0"]
        victim_runs = sorted(r for r, m in owners.items()
                             if m == victim)
        os.kill(members[victim].pid, signal.SIGKILL)
        members[victim].wait(10)
        print(f"federation-smoke: SIGKILLed {victim} "
              f"(owned {victim_runs})", flush=True)

        # Survivors must adopt; every run must re-list and re-park.
        owners2 = wait_runs_at(cli, seeds, target, timeout=240.0)
        if owners2 is None:
            return fail("runs never re-homed after the member kill")
        for rid in victim_runs:
            if owners2[rid] == victim:
                return fail(f"{rid} still listed on the dead member")
        doc = router.registry.members_doc()
        if doc.get("live") != n_members - 1 or doc.get("dead") != 1:
            return fail(f"registry census wrong after kill: {doc}")
        if obs.FED_MEMBERS.labels(state="dead").value != 1:
            return fail("gol_fed_members{state=dead} != 1")
        if obs.FED_FAILOVERS.value - failovers0 < 1:
            return fail("gol_fed_failovers_total never incremented")
        hz = healthz_doc().get("federation")
        if not hz or hz.get("dead") != 1 or len(hz["members"]) \
                != n_members:
            return fail(f"/healthz federation table wrong: {hz}")

        # Parity: every run — adopted or undisturbed — bit-identical
        # to the device torus replay of its seed, through the router.
        for rid, seed in seeds.items():
            bound = cli.for_run(rid)
            board = turn = None
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                try:
                    board, turn = bound.get_world()
                except Exception:
                    time.sleep(0.3)
                    continue
                if turn >= target:
                    break
                time.sleep(0.3)
            if board is None or turn != target:
                return fail(f"{rid}: no board at turn {target} "
                            f"(got turn {turn})")
            want = expected_board01(seed, target)
            if not np.array_equal((board != 0).astype(np.uint8), want):
                return fail(f"{rid}: post-failover board diverged "
                            f"from the device replay oracle")
        print(f"federation-smoke: all {n_runs} runs bit-identical at "
              f"turn {target} after failover ({len(victim_runs)} "
              f"adopted from {victim})", flush=True)
        print("federation-smoke: PASS", flush=True)
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(10)
        router.shutdown()


if __name__ == "__main__":
    rc = main()
    # os._exit dodges the known XLA daemon-thread teardown abort;
    # every gate already flushed its verdict.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
