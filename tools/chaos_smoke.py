"""chaos-smoke: prove the self-healing serving tier end to end on CPU.

Three acceptance gates (PR 10), real processes where the failure is a
process-level event:

  1. SIGTERM graceful drain — a real --fleet server with residents
     driving is SIGTERMed: it must exit 0 (drain, not crash) and leave
     a durable per-run manifest checkpoint for EVERY fleet resident
     (ck/run-<id>/), not just the legacy run;
  2. SIGKILL → restart quarantines nothing — a hard-killed fleet
     server's replacement serves a fresh run to completion with the
     fleet summary reporting zero quarantined runs: crash recovery is
     resume, never a false-positive fault verdict;
  3. poison → quarantine exactly once → auto-restore — in-process
     FleetEngine under GOL_CHAOS poison=<run>@<turn>: the fabricated
     device fault must quarantine the run EXACTLY once
     (gol_runs_quarantined_total{reason="popcount"} +1), auto-restore
     it from its own per-run checkpoint, and finish bit-identical to
     an uninjected run of the same seed.

Exit 0 = pass.

    make chaos-smoke    # bench.py --chaos + gate, then this
"""

from __future__ import annotations

import os
import signal
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fail(msg: str) -> int:
    print(f"chaos-smoke: FAIL — {msg}", flush=True)
    return 1


def _wait_turn(cli, run_id: str, turn: int, timeout: float = 90.0):
    """Poll ListRuns until `run_id` reaches `turn`; its final record."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        runs, summary = cli.list_runs()
        rec = next((r for r in runs if r["run_id"] == run_id), None)
        if rec is not None and rec["turn"] >= turn:
            return rec, summary
        time.sleep(0.1)
    return None, {}


def gate_drain(tmpdir: str) -> int:
    """Gate 1: SIGTERM drains — exit 0 + a durable manifest per run."""
    from gol_tpu.ckpt import manifest as mf
    from gol_tpu.client import RemoteEngine
    from tests.server_harness import spawn_server, wait_port

    ckdir = os.path.join(tmpdir, "ck_drain")
    proc = spawn_server(
        0, tmpdir, extra_args=("--fleet", "--checkpoint", ckdir,
                               "--ckpt-every", "4"))
    try:
        port = wait_port(proc)
        if not port:
            return fail("drain server never announced its port")
        cli = RemoteEngine(f"127.0.0.1:{port}", timeout=30.0)
        rng = np.random.default_rng(3)
        ids = []
        for i in range(2):
            board = (rng.random((64, 64)) < 0.3).astype(np.uint8)
            rec = cli.create_run(64, 64, board=board,
                                 run_id=f"drain{i}", ckpt_every=4,
                                 target_turn=10 ** 8)
            ids.append(rec["run_id"])
        for rid in ids:
            rec, _ = _wait_turn(cli, rid, 8)
            if rec is None:
                return fail(f"run {rid} never progressed")
        os.kill(proc.pid, signal.SIGTERM)
        try:
            rc = proc.wait(60)
        except Exception:
            return fail("SIGTERMed server did not exit")
        if rc != 0:
            return fail(f"drain exit code {rc}, want 0")
        for rid in ids:
            latest = mf.latest_checkpoint(os.path.join(ckdir,
                                                       f"run-{rid}"))
            if latest is None:
                return fail(f"no per-run drain checkpoint for {rid}")
            mf.verify_manifest(latest[1])
        print(f"chaos-smoke: SIGTERM drained, exit 0, per-run "
              f"checkpoints verified for {ids}", flush=True)
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)


def gate_restart(tmpdir: str) -> int:
    """Gate 2: SIGKILL → replacement serves cleanly, quarantines 0."""
    from gol_tpu.client import RemoteEngine
    from tests.server_harness import spawn_server, wait_port

    ckdir = os.path.join(tmpdir, "ck_kill")
    proc1 = spawn_server(
        0, tmpdir, extra_args=("--fleet", "--checkpoint", ckdir,
                               "--ckpt-every", "4"))
    proc2 = None
    try:
        port = wait_port(proc1)
        if not port:
            return fail("kill server never announced its port")
        cli = RemoteEngine(f"127.0.0.1:{port}", timeout=30.0)
        cli.create_run(64, 64, run_id="victim", ckpt_every=4,
                       target_turn=10 ** 8)
        if _wait_turn(cli, "victim", 8)[0] is None:
            return fail("victim never progressed before SIGKILL")
        os.kill(proc1.pid, signal.SIGKILL)
        proc1.wait(10)

        proc2 = spawn_server(
            0, tmpdir, extra_args=("--fleet", "--checkpoint", ckdir))
        port2 = wait_port(proc2)
        if not port2:
            return fail("replacement server never announced its port")
        cli2 = RemoteEngine(f"127.0.0.1:{port2}", timeout=30.0)
        rng = np.random.default_rng(5)
        board = (rng.random((64, 64)) < 0.3).astype(np.uint8)
        cli2.create_run(64, 64, board=board, run_id="after",
                        target_turn=32)
        rec, summary = _wait_turn(cli2, "after", 32)
        if rec is None:
            return fail("post-restart run never reached its target")
        if summary.get("quarantined", 0) != 0:
            return fail(f"restart quarantined runs: {summary}")
        print("chaos-smoke: SIGKILL→restart served a run to "
              "completion, quarantined=0", flush=True)
        return 0
    finally:
        for p in (proc1, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(10)


def gate_quarantine(tmpdir: str) -> int:
    """Gate 3: poisoned run quarantined exactly once, auto-restored
    from its per-run checkpoint, bit-identical to the clean run."""
    os.environ["GOL_CKPT"] = os.path.join(tmpdir, "ck_poison")
    from gol_tpu.fleet.engine import FleetEngine
    from gol_tpu.obs import catalog as obs

    rng = np.random.default_rng(0)
    board = (rng.random((64, 64)) < 0.3).astype(np.uint8)
    eng = FleetEngine(bucket_sizes=(64,), chunk_turns=4, slot_base=4)
    try:
        eng.create_run(64, 64, board=board.copy(), run_id="clean",
                       ckpt_every=8, target_turn=40)
        hc = eng._runs["clean"]
        if not hc.done.wait(60):
            return fail("clean fleet run did not finish")
        clean_board, clean_turn = eng._run_board(hc)

        q0 = obs.RUNS_QUARANTINED.labels(reason="popcount").value
        r0 = obs.RUNS_QUARANTINE_RESTORES.labels(status="ok").value
        os.environ["GOL_CHAOS"] = "poison=victim@20,seed=1"
        try:
            eng.create_run(64, 64, board=board.copy(), run_id="victim",
                           ckpt_every=8, target_turn=40)
            hv = eng._runs["victim"]
            if not hv.done.wait(60):
                return fail(f"poisoned run did not finish "
                            f"(state={hv.state})")
        finally:
            os.environ.pop("GOL_CHAOS", None)
        vb, vt = eng._run_board(hv)

        if vt != clean_turn:
            return fail(f"restored run at turn {vt}, clean at "
                        f"{clean_turn}")
        if not np.array_equal(vb, clean_board):
            return fail("restored run diverged from the clean run")
        dq = obs.RUNS_QUARANTINED.labels(reason="popcount").value - q0
        dr = obs.RUNS_QUARANTINE_RESTORES.labels(status="ok").value - r0
        if dq != 1:
            return fail(f"quarantined {dq} times, want exactly 1")
        if dr != 1:
            return fail(f"restored {dr} times, want exactly 1")
        if hv.describe().get("quarantine_reason") != "popcount":
            return fail(f"describe lacks the quarantine record: "
                        f"{hv.describe()}")
        if eng.runs_summary().get("quarantined", 0) != 0:
            return fail("a recovered run still counts as quarantined")
        print(f"chaos-smoke: poisoned run quarantined exactly once, "
              f"auto-restored (tries={hv.quarantine_tries}), "
              f"bit-identical at turn {vt}", flush=True)
        return 0
    finally:
        for rid in ("clean", "victim"):
            try:
                eng.destroy_run(rid)
            except Exception:
                pass
        eng.kill_prog()
        os.environ.pop("GOL_CKPT", None)


def main() -> int:
    tmpdir = tempfile.mkdtemp(prefix="gol_chaos_smoke_")
    rc = gate_drain(tmpdir)
    rc = rc or gate_restart(tmpdir)
    rc = rc or gate_quarantine(tmpdir)
    if rc == 0:
        print("chaos-smoke: PASS", flush=True)
    return rc


if __name__ == "__main__":
    rc = main()
    # os._exit dodges the known XLA daemon-thread teardown abort (the
    # in-process FleetEngine's loop/writer threads at interpreter
    # exit); every gate already flushed its verdict.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
