"""fleet-top: a terminal dashboard for the fleet telemetry plane.

Polls a federation router's GetTelemetry / GetAudit wire methods (PR
16) and renders, once per interval:

  * the fleet rollup line — resident runs, aggregate CUPS, queue
    depth, staleness p99, imbalance ratio, live/dead member counts;
  * a per-member table from the registry's snapshot states;
  * active alerts (rule + how long they have been firing);
  * the tail of the gol-fleet-audit/1 log (newest last), streamed
    incrementally by `since_seq` so each frame only fetches records
    it has not seen;
  * with `--journal-run RUN_ID`, that run's hash-chained gol-journal/1
    tail (GetJournal, proxied by the router to the run's owner) — the
    black box pane: chain head, last seq, newest events;
  * with `--usage`, the top-talkers pane (PR 19): per-run device-time
    share, wire bytes in/out, broadcast bytes, plus the fleet usage
    rollup and capacity headroom rows from GetUsage/GetTelemetry.

    python tools/fleet_top.py --router HOST:PORT            # live
    python tools/fleet_top.py --router HOST:PORT --once     # one frame

`--once` prints a single frame and exits 0 — that head-less mode is
what tools/fleet_obs_smoke.py runs in CI. Rendering is pure
(`render(doc, records)` returns a string), so the smoke can also call
it in-process on a fetched doc.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gol_tpu.client import RemoteEngine  # noqa: E402


def _si(v: float) -> str:
    """1234567 -> '1.2M' — compact engineering notation."""
    for div, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= div:
            return f"{v / div:.1f}{suffix}"
    return f"{v:.0f}"


def render(doc: dict, records: list, now: float = None,
           journal: dict = None, usage: dict = None) -> str:
    """One dashboard frame from a GetTelemetry doc, an audit tail
    (oldest first), optionally one run's GetJournal tail, and
    optionally a member GetUsage doc. Pure string building — no I/O,
    no client."""
    if now is None:
        now = time.time()
    fleet = doc.get("fleet", {})
    lines = []
    lines.append(
        "fleet  runs={runs}  cups={cups}  queue={q}  "
        "stale_p99={st:.0f}ms  imbalance={imb:.2f}  "
        "members={live} live / {dead} dead".format(
            runs=fleet.get("runs_resident", 0),
            cups=_si(float(fleet.get("cups", 0.0))),
            q=fleet.get("queue_depth", 0),
            st=float(fleet.get("staleness_p99_ms", 0.0)),
            imb=float(fleet.get("imbalance_ratio", 1.0)),
            live=fleet.get("members_live", 0),
            dead=fleet.get("members_dead", 0)))
    tsdb = doc.get("tsdb", {})
    payload = doc.get("payload_bytes", {})
    lines.append(
        "plane  tsdb {series} series / {pts} pts  "
        "snap_p99={p99}B  audit_seq={seq}".format(
            series=tsdb.get("series", 0),
            pts=tsdb.get("points_total", 0),
            p99=payload.get("p99", "-"),
            seq=doc.get("audit_seq", 0)))
    lines.append("")

    members = doc.get("members", {})
    lines.append(f"{'MEMBER':<22} {'RUNS':>5} {'QUEUE':>6} "
                 f"{'CUPS':>8} {'STALE_P99':>10} {'SLO':>4}")
    for mid, row in sorted(members.items()):
        lines.append(
            f"{mid:<22} {row.get('resident', 0):>5} "
            f"{row.get('queue_depth', 0):>6} "
            f"{_si(float(row.get('cups', 0.0))):>8} "
            f"{row.get('staleness_p99_ms', 0.0):>8.0f}ms "
            f"{row.get('slo_breaches', 0):>4}")
    if not members:
        lines.append("  (no members reporting)")
    lines.append("")

    alerts = doc.get("alerts", {})
    active = alerts.get("active", {})
    if active:
        for rule, st in sorted(active.items()):
            since = float(st.get("since", now))
            lines.append(
                f"ALERT  {rule}  value={st.get('value')}  "
                f"firing {max(0.0, now - since):.0f}s")
    else:
        lines.append("alerts: none active")
    lines.append("")

    lines.append("audit (newest last):")
    for rec in records[-10:]:
        extra = " ".join(
            f"{k}={rec[k]}" for k in
            ("member", "run_id", "rule", "reason", "phase", "target")
            if k in rec)
        lines.append(f"  #{rec.get('seq', '?'):>4} "
                     f"{rec.get('kind', '?'):<16} {extra}")
    if not records:
        lines.append("  (empty)")

    if journal is not None:
        lines.append("")
        head = str(journal.get("head") or "")[:16]
        lines.append(
            f"journal {journal.get('run_id', '?')}  "
            f"seq={journal.get('seq', -1)}  head={head}…")
        for rec in journal.get("records", [])[-10:]:
            extra = " ".join(
                f"{k}={rec[k]}" for k in
                ("turn", "rule", "seed_kind", "reason", "repr")
                if k in rec)
            sha = str(rec.get("board_sha256", ""))[:10]
            if sha:
                extra = f"{extra} sha={sha}…" if extra else f"sha={sha}…"
            lines.append(f"  #{rec.get('seq', '?'):>4} "
                         f"{rec.get('kind', '?'):<12} {extra}")
        if journal.get("error"):
            lines.append(f"  (journal unavailable: {journal['error']})")
        elif not journal.get("records"):
            lines.append("  (no journal records)")

    if usage is not None:
        lines.append("")
        att = usage.get("attribution", {})
        fleet_use = fleet.get("usage", {})
        lines.append(
            "usage  tracked={trk}  attributed={att_s:.2f}s "
            "(err={err:.2f}%)  headroom={adm} runs / "
            "{hr} cups".format(
                trk=usage.get("runs_tracked", 0),
                att_s=float(att.get("attributed_s", 0.0)),
                err=float(att.get("error_pct", 0.0)),
                adm=fleet_use.get("admissible_runs",
                                  _best_admissible(usage)),
                hr=_si(float(fleet_use.get(
                    "cups_headroom", _sum_headroom(usage))))))
        top = usage.get("top", [])
        lines.append(f"{'RUN':<22} {'DEV_SHARE':>9} {'TURNS':>8} "
                     f"{'WIRE_IN':>8} {'WIRE_OUT':>9} {'BCAST':>8}")
        for row in top:
            lines.append(
                f"{str(row.get('run_id', '?'))[:22]:<22} "
                f"{row.get('share_pct', 0.0):>8.1f}% "
                f"{_si(float(row.get('turns', 0))):>8} "
                f"{_si(float(row.get('wire_in', 0))):>8}B "
                f"{_si(float(row.get('wire_out', 0))):>8}B "
                f"{_si(float(row.get('bc_bytes', 0) + row.get('sent_bytes', 0))):>7}B")
        if not top:
            lines.append("  (no talkers metered)")
        if usage.get("error"):
            lines.append(f"  (usage unavailable: {usage['error']})")
    return "\n".join(lines)


def _best_admissible(usage: dict) -> int:
    return max((int(r.get("admissible", 0))
                for r in usage.get("capacity", [])), default=0)


def _sum_headroom(usage: dict) -> float:
    return sum(float(r.get("cups_headroom", 0.0))
               for r in usage.get("capacity", []))


def fetch_frame(client: RemoteEngine, since_seq: int) -> tuple:
    """(telemetry_doc, new_audit_records) — one poll of the router."""
    doc = client.get_telemetry()
    records = client.get_audit(since_seq=since_seq, limit=200)
    return doc, records


def fetch_journal(router: str, run_id: str,
                  timeout: float = 10.0) -> dict:
    """One run's journal tail via the router (a run-scoped client so
    the run_id header routes GetJournal to the owning member). Errors
    render in-pane instead of killing the dashboard."""
    try:
        cli = RemoteEngine(router, timeout=timeout, run_id=run_id)
        j = cli.get_journal(limit=50)
        j["run_id"] = run_id
        return j
    except Exception as e:
        return {"run_id": run_id, "head": "", "seq": -1, "records": [],
                "error": f"{type(e).__name__}: {e}"}


def fetch_usage(client: RemoteEngine) -> dict:
    """One GetUsage poll. Errors render in-pane instead of killing
    the dashboard (a pre-PR-19 peer answers 'unknown method')."""
    try:
        return client.get_usage()
    except Exception as e:
        return {"runs_tracked": 0, "top": [],
                "error": f"{type(e).__name__}: {e}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="terminal dashboard over GetTelemetry/GetAudit")
    ap.add_argument("--router", required=True,
                    help="federation router HOST:PORT")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print a single frame and exit (CI mode)")
    ap.add_argument("--journal-run", default="", metavar="RUN_ID",
                    help="also render RUN_ID's gol-journal/1 tail "
                         "(GetJournal via the router)")
    ap.add_argument("--usage", action="store_true",
                    help="also render the top-talkers pane "
                         "(GetUsage: device-time share, wire and "
                         "broadcast bytes per run)")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    client = RemoteEngine(args.router, timeout=args.timeout)
    seen_seq = 0
    tail: list = []
    try:
        while True:
            doc, fresh = fetch_frame(client, seen_seq)
            for rec in fresh:
                seen_seq = max(seen_seq, int(rec.get("seq", 0)))
            tail = (tail + fresh)[-200:]
            jrn = (fetch_journal(args.router, args.journal_run,
                                 timeout=args.timeout)
                   if args.journal_run else None)
            use = fetch_usage(client) if args.usage else None
            frame = render(doc, tail, journal=jrn, usage=use)
            if args.once:
                print(frame)
                return 0
            # Full-screen repaint: clear + home, then the frame.
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
