"""conv-smoke: prove the conv/FFT kernel tier end to end, fast.

Small boards only — parity and plumbing, NOT policy timing (tier
choice at these sizes is dispatch-noise; `bench.py --conv` owns the
gated crossover measurement at 4096²). Checks:

  * conv and fft tiers are BIT-identical to the independent numpy
    summed-area oracle for Larger-than-Life rules at r=1 (Conway's
    B3/S23 as an LtL rule) and r=5 (Bosco's Rule), non-pow2 board;
  * the Lenia float32 step tracks the float64 numpy oracle within
    1e-4 max-abs over 4 turns, on BOTH tiers;
  * a real Engine run (server_distributor) of each family lands on
    the same oracle trajectory, and a Lenia engine serves a lossless
    f32 frame to a CAP_F32 peer;
  * `select_tier` policy surface: env forcing honored, float boards
    never choose a packed tier, unknown names refused;
  * `gol_conv_dispatches_total{tier=...}` / one-hot `gol_kernel_tier`
    hold real samples in the registry after the runs.

Exit 0 = pass.

    make conv-smoke     # part of the `make smoke` chain
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    import jax.numpy as jnp

    from gol_tpu import wire
    from gol_tpu.engine import Engine
    from gol_tpu.models import lenia as lenia_mod
    from gol_tpu.models.largerthanlife import BOSCO, CONWAY_LTL, \
        run_turns_np
    from gol_tpu.ops import conv as conv_ops
    from gol_tpu.params import Params

    problems = []

    def check(ok, what):
        print(f"conv-smoke: {'ok  ' if ok else 'FAIL'} {what}")
        if not ok:
            problems.append(what)

    rng = np.random.default_rng(0)

    # ---- LtL parity: both tiers vs the numpy oracle --------------------
    b = (rng.random((96, 80)) < 0.35).astype(np.uint8)
    for rule, turns in ((CONWAY_LTL, 8), (BOSCO, 4)):
        want = np.asarray(run_turns_np(b, turns, rule), dtype=np.uint8)
        for tier in ("conv", "fft"):
            got = np.asarray(conv_ops.run_turns(
                jnp.asarray(b), turns, rule, tier=tier), dtype=np.uint8)
            check(np.array_equal(got, want),
                  f"{tier} bit-identical vs oracle "
                  f"({rule.rulestring}, {turns} turns, 96x80)")

    # ---- Lenia parity: float32 jax vs float64 numpy --------------------
    rule = lenia_mod.ORBIUM
    s0 = lenia_mod.seed_board(96, 96, 7, rule)
    ref = s0
    for _ in range(4):
        ref = lenia_mod.step_np(ref, rule)
    for tier in ("conv", "fft"):
        got = np.asarray(conv_ops.run_turns(
            jnp.asarray(s0), 4, rule, tier=tier))
        err = float(np.max(np.abs(got.astype(np.float64)
                                  - ref.astype(np.float64))))
        check(err < 1e-4,
              f"lenia {tier} max-abs {err:.2e} < 1e-4 vs float64 "
              f"oracle (4 turns, 96x96)")

    # ---- Engine end to end ---------------------------------------------
    eng = Engine(rule=BOSCO)
    p = Params(threads=1, image_width=80, image_height=96, turns=4)
    out, turn = eng.server_distributor(p, b * np.uint8(255))
    want = np.asarray(run_turns_np(b, 4, BOSCO), dtype=np.uint8)
    check(turn == 4 and np.array_equal(
        (np.asarray(out) != 0).astype(np.uint8), want),
        "Engine(BOSCO) trajectory bit-identical vs oracle")

    eng = Engine(rule=rule)
    p = Params(threads=1, image_width=96, image_height=96, turns=4)
    out, turn = eng.server_distributor(p, s0)
    frame, fturn = eng.get_world_frame(frozenset({wire.CAP_F32}))
    # Round-trip the frame through the real wire codec path.
    import socket
    import threading

    a, bsock = socket.socketpair()
    a.settimeout(10)
    bsock.settimeout(10)
    box = {}
    t = threading.Thread(
        target=lambda: box.update(resp=wire.recv_msg(bsock)))
    t.start()
    wire.send_msg(a, {"ok": True}, frame=frame)
    t.join(10)
    a.close()
    bsock.close()
    _, got = box["resp"]
    err = float(np.max(np.abs(got.astype(np.float64)
                              - ref.astype(np.float64))))
    check(turn == 4 and fturn == 4 and err < 1e-4,
          f"Engine(ORBIUM) f32 frame max-abs {err:.2e} < 1e-4 vs "
          f"oracle")
    check(eng.frames_diffable is False,
          "float boards refuse frame diffing (frames_diffable)")

    # ---- policy surface ------------------------------------------------
    saved = os.environ.pop(conv_ops.TIER_ENV, None)
    try:
        check(conv_ops.select_tier(4096, 4096, 1, "uint8")
              in ("bitplane", "fused"),
              "r=1 binary stays on a packed tier")
        check(conv_ops.select_tier(1024, 1024, 13, "float32") == "fft",
              "float boards auto-select fft")
        os.environ[conv_ops.TIER_ENV] = "fft"
        check(conv_ops.select_tier(64, 64, 1, "uint8") == "fft",
              f"{conv_ops.TIER_ENV}=fft forces the tier")
        os.environ[conv_ops.TIER_ENV] = "warp"
        try:
            conv_ops.select_tier(64, 64, 1, "uint8")
            check(False, "unknown tier name refused")
        except ValueError:
            check(True, "unknown tier name refused")
    finally:
        if saved is None:
            os.environ.pop(conv_ops.TIER_ENV, None)
        else:
            os.environ[conv_ops.TIER_ENV] = saved

    # ---- registry families ---------------------------------------------
    from gol_tpu.obs.metrics import REGISTRY

    samples = {}
    for line in REGISTRY.render_prometheus().splitlines():
        if line.startswith("#") or " " not in line:
            continue
        key, _, val = line.rpartition(" ")
        try:
            samples[key] = float(val)
        except ValueError:
            pass
    for tier in ("conv", "fft"):
        key = f'gol_conv_dispatches_total{{tier="{tier}"}}'
        check(samples.get(key, 0) > 0,
              f"registry sample populated: {key}")
    onehot = sum(samples.get(f'gol_kernel_tier{{tier="{t}"}}', 0.0)
                 for t in conv_ops.TIERS)
    check(onehot == 1.0,
          f"gol_kernel_tier is one-hot (sum={onehot})")

    if problems:
        print(f"conv-smoke: {len(problems)} problem(s)")
        return 1
    print("conv-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
