"""usage-smoke: prove the per-run usage metering plane end to end.

One in-process acceptance scenario (PR 19) against a real fleet
server socket:

  * a --fleet EngineServer admits three runs and drives them; the
    usage meter must attribute device time to every one of them with
    the conservation invariant holding (sum of per-run shares within
    1% of the measured dispatch wall);
  * `GetUsage` over the wire returns the bounded top-talkers doc, and
    a run-scoped client additionally gets its own live record (wire
    bytes charged by the server dispatch tail must be nonzero — this
    very RPC pays for itself);
  * the /healthz body carries the same doc under "usage" (reference-
    read, PR-8 posture: no per-run metric labels anywhere);
  * `fleet_top.py --usage` renders the pane headlessly from the
    fetched doc (pure render call, same code path as --once);
  * DestroyRun retires the run and writes its final "usage" record
    into the hash-chained gol-journal/1 black box.

Exit 0 = pass.

    make usage-smoke
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RUNS = 3
SIZE = 128


def fail(msg: str) -> int:
    print(f"usage-smoke: FAIL — {msg}", flush=True)
    return 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("GOL_CHAOS", None)
    tmpdir = tempfile.mkdtemp(prefix="gol_usage_smoke_")
    # Journal on (the destroy-time usage record lands there); flush
    # throttle off so every usage_doc() read is rebuilt fresh.
    os.environ["GOL_JOURNAL"] = os.path.join(tmpdir, "journal")
    os.environ["GOL_USAGE_FLUSH_S"] = "0"

    from gol_tpu import journal
    from gol_tpu.client import RemoteEngine
    from gol_tpu.fleet import FleetEngine
    from gol_tpu.obs import usage as obs_usage
    from gol_tpu.obs.http import healthz_doc
    from gol_tpu.server import EngineServer
    from tools import fleet_top

    obs_usage.METER.reset()
    eng = FleetEngine(bucket_sizes=(SIZE,), slot_base=max(RUNS, 8))
    srv = EngineServer(port=0, host="127.0.0.1", engine=eng)
    srv.start_background()
    addr = f"127.0.0.1:{srv.port}"
    cli = RemoteEngine(addr, timeout=30.0)
    rids = [f"u{i}" for i in range(RUNS)]
    try:
        for rid in rids:
            cli.create_run(SIZE, SIZE, run_id=rid, target_turn=10_000)

        # Drive until every run has progressed and the meter has
        # attributed device time to each of them.
        deadline = time.monotonic() + 120.0
        doc = {}
        while time.monotonic() < deadline:
            doc = obs_usage.usage_doc()
            top_ids = {r.get("run_id") for r in doc.get("top", [])}
            if (set(rids) <= top_ids
                    and all(r.get("device_s", 0.0) > 0
                            for r in doc["top"])):
                break
            time.sleep(0.2)
        else:
            return fail(f"meter never attributed all {RUNS} runs "
                        f"(doc: {doc})")
        att = doc.get("attribution", {})
        if not att.get("wall_s", 0.0) > 0:
            return fail(f"no dispatch wall measured: {att}")
        if abs(float(att.get("error_pct", 100.0))) > 1.0:
            return fail(f"conservation violated: {att}")
        print(f"usage-smoke: {RUNS} runs attributed, wall "
              f"{att['wall_s']:.3f}s err {att['error_pct']:.4f}%",
              flush=True)

        # GetUsage over the wire — fleet doc plus the run-scoped view;
        # the RPC itself must have been charged to the run it names.
        wire_doc = cli.get_usage()
        if wire_doc.get("runs_tracked", 0) < RUNS:
            return fail(f"GetUsage runs_tracked: {wire_doc}")
        rcli = RemoteEngine(addr, timeout=30.0, run_id=rids[0])
        mine = rcli.get_usage().get("run", {})
        if mine.get("run_id") != rids[0]:
            return fail(f"run-scoped GetUsage record: {mine}")
        for _ in range(2):  # second poll sees the first one's bytes
            mine = rcli.get_usage().get("run", {})
        if not (mine.get("wire_in", 0) > 0 and mine.get("wire_out", 0) > 0):
            return fail(f"GetUsage RPC not charged to its run: {mine}")
        if not wire_doc.get("capacity"):
            return fail("no capacity headroom rows on the wire doc")
        print("usage-smoke: GetUsage serves top-K + capacity rows; "
              f"{rids[0]} charged wire_in={mine['wire_in']}B "
              f"wire_out={mine['wire_out']}B", flush=True)

        # /healthz carries the doc (reference read, no metric labels).
        hz = healthz_doc()
        if hz.get("usage", {}).get("runs_tracked", 0) < RUNS:
            return fail(f"/healthz usage doc: {hz.get('usage')}")

        # Headless fleet_top --usage pane over the fetched doc.
        frame = fleet_top.render({}, [], usage=wire_doc)
        if "usage  tracked=" not in frame or rids[0] not in frame:
            return fail(f"fleet_top usage pane:\n{frame}")
        print("usage-smoke: /healthz doc + fleet_top pane render",
              flush=True)

        # DestroyRun writes the final usage record into the journal.
        cli.destroy_run(rids[0])
        jpath = journal.journal_path(rids[0])
        records, torn = journal.load_records(jpath)
        if torn is not None:
            return fail(f"journal torn line at {torn}")
        urec = next((r for r in records if r.get("kind") == "usage"),
                    None)
        if urec is None:
            return fail("no final usage record in the journal "
                        f"(kinds: {sorted({r.get('kind') for r in records})})")
        if not (urec.get("device_s", 0.0) > 0
                and urec.get("turns", 0) > 0):
            return fail(f"empty final usage record: {urec}")
        try:
            obs_usage.METER.run_doc(rids[0])
            return fail("destroyed run still tracked by the meter")
        except KeyError:
            pass
        print(f"usage-smoke: destroy wrote final usage record "
              f"(device_s={urec['device_s']:.4f}, "
              f"turns={urec['turns']})", flush=True)
        print("usage-smoke: PASS", flush=True)
        return 0
    finally:
        try:
            eng.kill_prog()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
        srv.shutdown()


if __name__ == "__main__":
    rc = main()
    # os._exit dodges the known XLA daemon-thread teardown abort;
    # every gate already flushed its verdict.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
