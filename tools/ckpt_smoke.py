"""ckpt-smoke: prove the checkpoint/restore subsystem end to end on CPU.

Three acceptance gates, real processes where it matters:

  1. kill→resume roundtrip — an engine server with `--checkpoint
     --ckpt-every` is SIGKILLed mid-run; a replacement `--resume DIR`
     process serves the newest durable checkpoint and finishes the run
     bit-identical to the independent numpy oracle;
  2. hash-mismatch refusal — a corrupted payload fails `verify` and a
     restore attempt raises CheckpointIntegrityError;
  3. retention safety — GC under keep-last + keep-every never deletes
     the newest durable checkpoint, and every survivor still verifies.

Exit 0 = pass.

    make ckpt-smoke     # JAX_PLATFORMS=cpu python tools/ckpt_smoke.py
"""

from __future__ import annotations

import os
import signal
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def fail(msg: str) -> int:
    print(f"ckpt-smoke: FAIL — {msg}")
    return 1


def main() -> int:
    from gol_tpu import ckpt
    from gol_tpu.ckpt import manifest as mf
    from gol_tpu.client import RemoteEngine
    from gol_tpu.ops.reference import run_turns_np
    from gol_tpu.params import Params
    from tests.server_harness import spawn_server, wait_port

    tmpdir = tempfile.mkdtemp(prefix="gol_ckpt_smoke_")
    ckdir = os.path.join(tmpdir, "ck")

    # -- gate 1: kill → resume roundtrip across real processes --------
    proc1 = spawn_server(
        0, tmpdir, extra_env={"GOL_MAX_CHUNK": "8"},
        extra_args=("--checkpoint", ckdir, "--ckpt-every", "8",
                    "--ckpt-keep", "4"))
    proc2 = None
    try:
        port = wait_port(proc1)
        if not port:
            return fail("server 1 never announced its port")
        rng = np.random.default_rng(9)
        world0 = ((rng.random((64, 64)) < 0.3).astype(np.uint8)) * 255
        eng = RemoteEngine(f"127.0.0.1:{port}", timeout=30.0)

        def run():
            try:
                eng.server_distributor(
                    Params(threads=2, image_width=64, image_height=64,
                           turns=10**8), world0)
            except Exception:
                pass  # dies with the SIGKILL — expected

        threading.Thread(target=run, daemon=True).start()
        deadline = time.monotonic() + 120
        while True:
            latest = mf.latest_checkpoint(ckdir)
            if latest is not None and latest[0] >= 24:
                break
            if time.monotonic() > deadline:
                return fail("no durable checkpoint appeared")
            time.sleep(0.05)
        os.kill(proc1.pid, signal.SIGKILL)
        proc1.wait(10)

        turn0, manifest_path, _ = mf.latest_checkpoint(ckdir)
        mf.verify_manifest(manifest_path)  # survived the kill intact

        proc2 = spawn_server(0, tmpdir, resume=ckdir)
        port2 = wait_port(proc2)
        if not port2:
            return fail("replacement server never announced its port")
        eng2 = RemoteEngine(f"127.0.0.1:{port2}", timeout=30.0)
        w2, t2 = eng2.get_world()
        if t2 != turn0:
            return fail(f"resumed turn {t2} != checkpoint turn {turn0}")
        final, tf = eng2.server_distributor(
            Params(threads=2, image_width=64, image_height=64,
                   turns=40), w2, start_turn=t2)
        want = run_turns_np((world0 != 0).astype(np.uint8), tf)
        if not np.array_equal((final != 0).astype(np.uint8), want):
            return fail("resumed run diverged from the oracle")
        print(f"ckpt-smoke: kill at turn>={turn0}, resumed to {tf}, "
              "bit-identical vs oracle")
    finally:
        for p in (proc1, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(10)

    # -- gate 2: hash mismatch refused ---------------------------------
    payload = mf.payload_path(manifest_path, mf.read_manifest(manifest_path))
    raw = bytearray(open(payload, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(payload, "wb") as f:
        f.write(raw)
    try:
        mf.verify_manifest(manifest_path)
        return fail("corrupted payload verified clean")
    except ckpt.CheckpointIntegrityError:
        pass
    from gol_tpu.engine import Engine
    try:
        Engine().restore_run(manifest_path)
        return fail("engine restored a corrupted checkpoint")
    except ckpt.CheckpointIntegrityError:
        print("ckpt-smoke: corrupted checkpoint refused (verify + restore)")

    # -- gate 3: retention never deletes the newest durable ------------
    rdir = os.path.join(tmpdir, "ret")
    w = ckpt.CheckpointWriter(rdir, run_id="smoke",
                              keep_last=2, keep_every=100)
    cells = np.zeros((8, 8), np.uint8)
    for turn in (50, 100, 150, 200, 250):
        w.write_sync(ckpt.Snapshot(cells, "u8", 0, turn, (8, 8),
                                   "B3/S23"))
        newest = mf.latest_checkpoint(rdir)
        if newest is None or newest[0] != turn:
            return fail(f"retention deleted the newest durable ({turn})")
    turns = [t for t, _, _ in ckpt.list_checkpoints(rdir)]
    if turns != [100, 200, 250]:
        return fail(f"retention kept {turns}, want [100, 200, 250]")
    for _, p, _ in ckpt.list_checkpoints(rdir):
        mf.verify_manifest(p)
    print(f"ckpt-smoke: retention kept {turns} "
          "(last 2 + keep-every-100 pins), all verified")

    print("ckpt-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
