// Native runtime layer for the TPU GoL framework.
//
// Role parity with the reference's single native dependency — libSDL2
// reached through cgo (`Local/sdl/window.go:4`) — plus the host-side data
// plane the Go version does in its io goroutine (`Local/gol/io.go:42-121`):
//
//   * PGM P5 codec: single-pass read/validate/write; the Python fallback
//     needs several array passes, which matters at 65536² (4.3 GB).
//   * Bit pack/unpack: {0,255} pixels ⇄ 32 cells/uint32, LSB-first —
//     byte-layout identical to gol_tpu/ops/bitpack.py.
//   * Popcount: alive count of a packed board.
//   * Half-block frame renderer: board → UTF-8 ANSI frame (two board rows
//     per character line), the terminal stand-in for the SDL texture.
//   * uint64 bit-parallel torus stepper: host CPU engine for oracle
//     cross-checks and TPU-less operation (the reference's worker compute
//     role, `SubServer/distributor.go:119-208`, as carry-save adders
//     instead of per-cell branches).
//
// C ABI only (consumed via ctypes from gol_tpu/native.py). All functions
// return 0 on success or a negative errno-style code.

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int kMaxval = 255;

// Read at most `cap` leading bytes — the header tokenizer never needs the
// payload, and slurping a 65536² file (4.3 GB) just to parse a ~20-byte
// header would defeat the codec's single-pass design.
int read_prefix(const char* path, size_t cap, std::string* out) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  out->resize(cap);
  size_t got = std::fread(&(*out)[0], 1, cap, f);
  if (got < cap && std::ferror(f)) { std::fclose(f); return -3; }
  std::fclose(f);
  out->resize(got);
  return 0;
}

// strtol with whole-token validation: "12abc" is a header error, not 12
// (matches the Python tokenizer's int() strictness), and an out-of-range
// token is an error rather than a silent clamp to LONG_MAX.
bool parse_dim(const std::string& tok, long* out) {
  if (tok.empty()) return false;
  char* end = nullptr;
  errno = 0;
  long v = std::strtol(tok.c_str(), &end, 10);
  if (errno == ERANGE || end == nullptr || *end != '\0') return false;
  *out = v;
  return true;
}

// Whitespace-delimited header token, '#' comments skipped.
bool next_token(const std::string& buf, size_t* pos, std::string* tok) {
  size_t n = buf.size(), p = *pos;
  while (p < n) {
    if (buf[p] == '#') { while (p < n && buf[p] != '\n') ++p; }
    else if (std::isspace(static_cast<unsigned char>(buf[p]))) ++p;
    else break;
  }
  size_t start = p;
  while (p < n && !std::isspace(static_cast<unsigned char>(buf[p]))) ++p;
  *pos = p;
  if (start == p) return false;
  tok->assign(buf, start, p - start);
  return true;
}

}  // namespace

extern "C" {

// Parse the P5 header: fills (*w, *h) and *payload_off (offset of the
// first payload byte). Returns 0, or <0 on malformed/mismatched input.
int gol_pgm_read_header(const char* path, int64_t* w, int64_t* h,
                        int64_t* payload_off) {
  // 64 KB bounds even comment-heavy headers; the payload is never needed.
  std::string buf;
  if (int rc = read_prefix(path, 64 * 1024, &buf)) return rc;
  size_t pos = 0;
  std::string tok;
  if (!next_token(buf, &pos, &tok) || tok != "P5") return -10;
  std::string ws, hs, ms;
  if (!next_token(buf, &pos, &ws) || !next_token(buf, &pos, &hs) ||
      !next_token(buf, &pos, &ms))
    return -11;
  long wv, hv, mv;
  if (!parse_dim(ws, &wv) || !parse_dim(hs, &hv) || !parse_dim(ms, &mv))
    return -11;
  if (wv <= 0 || hv <= 0) return -12;
  if (mv != kMaxval) return -13;  // reference contract: maxval MUST be 255
  *w = wv;
  *h = hv;
  *payload_off = static_cast<int64_t>(pos) + 1;  // one ws byte ends header
  return 0;
}

// Read the payload directly into `out` (caller-sized w*h), validating
// {0,255} — a seek + one fread, no intermediate buffer (at 65536² the
// payload is 4.3 GB; slurping it twice would dwarf the Python fallback).
int gol_pgm_read_payload(const char* path, int64_t payload_off,
                         uint8_t* out, int64_t count) {
  if (payload_off < 0 || count < 0) return -20;
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, static_cast<long>(payload_off), SEEK_SET) != 0) {
    std::fclose(f);
    return -20;
  }
  size_t got = std::fread(out, 1, static_cast<size_t>(count), f);
  std::fclose(f);
  if (got != static_cast<size_t>(count)) return -20;
  for (int64_t i = 0; i < count; ++i) {
    uint8_t v = out[i];
    if (v != 0 && v != kMaxval) return -21;
  }
  return 0;
}

int gol_pgm_write(const char* path, const uint8_t* board, int64_t w,
                  int64_t h) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  std::fprintf(f, "P5\n%lld %lld\n%d\n", static_cast<long long>(w),
               static_cast<long long>(h), kMaxval);
  size_t n = static_cast<size_t>(w) * static_cast<size_t>(h);
  size_t put = std::fwrite(board, 1, n, f);
  int rc = std::fclose(f);
  return (put == n && rc == 0) ? 0 : -4;
}

// {0,255} (or {0,1}) pixels → packed words, 32 cells/word LSB-first.
// w must be a multiple of 32 (caller-checked).
void gol_pack_bits(const uint8_t* pixels, uint32_t* words, int64_t h,
                   int64_t w) {
  int64_t wp = w / 32;
  for (int64_t r = 0; r < h; ++r) {
    const uint8_t* row = pixels + r * w;
    uint32_t* wrow = words + r * wp;
    for (int64_t c = 0; c < wp; ++c) {
      uint32_t v = 0;
      for (int b = 0; b < 32; ++b)
        v |= static_cast<uint32_t>(row[c * 32 + b] != 0) << b;
      wrow[c] = v;
    }
  }
}

// Packed words → {0,255} pixels.
void gol_unpack_bits(const uint32_t* words, uint8_t* pixels, int64_t h,
                     int64_t w) {
  int64_t wp = w / 32;
  for (int64_t r = 0; r < h; ++r) {
    const uint32_t* wrow = words + r * wp;
    uint8_t* row = pixels + r * w;
    for (int64_t c = 0; c < wp; ++c) {
      uint32_t v = wrow[c];
      for (int b = 0; b < 32; ++b)
        row[c * 32 + b] = (v >> b) & 1 ? kMaxval : 0;
    }
  }
}

int64_t gol_popcount_words(const uint32_t* words, int64_t n) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i)
    total += __builtin_popcount(words[i]);
  return total;
}

// Render a {0,255} board as a UTF-8 half-block frame: two board rows per
// character line (' ', '▀', '▄', '█'), '\n'-terminated lines. Writes at
// most `cap` bytes into `out`; returns bytes written, or -1 if `cap` is
// too small (worst case 3*w + 1 bytes per line, ceil(h/2) lines).
int64_t gol_render_halfblocks(const uint8_t* pixels, int64_t h, int64_t w,
                              char* out, int64_t cap) {
  static const char* kGlyph[4] = {" ", "\xE2\x96\x80", "\xE2\x96\x84",
                                  "\xE2\x96\x88"};
  static const int kLen[4] = {1, 3, 3, 3};
  int64_t pos = 0;
  for (int64_t r = 0; r < h; r += 2) {
    for (int64_t c = 0; c < w; ++c) {
      int top = pixels[r * w + c] != 0;
      int bot = (r + 1 < h) ? pixels[(r + 1) * w + c] != 0 : 0;
      int g = top | (bot << 1);
      if (pos + kLen[g] + 1 > cap) return -1;
      std::memcpy(out + pos, kGlyph[g], kLen[g]);
      pos += kLen[g];
    }
    out[pos++] = '\n';
  }
  return pos;
}

// One Conway turn on a torus, 64 cells/word LSB-first; wq words per row.
// Carry-save adder network with self-inclusive counts — the same math the
// pallas kernel runs on the TPU VPU (gol_tpu/ops/pallas_stencil.py),
// word-level on the host CPU.
void gol_step_torus_u64(const uint64_t* in, uint64_t* out, int64_t h,
                        int64_t wq) {
  std::vector<uint64_t> hs0(static_cast<size_t>(h) * wq);
  std::vector<uint64_t> hs1(static_cast<size_t>(h) * wq);
  // Horizontal (west + self + east) per cell, torus across words.
  for (int64_t r = 0; r < h; ++r) {
    const uint64_t* row = in + r * wq;
    for (int64_t c = 0; c < wq; ++c) {
      uint64_t self = row[c];
      uint64_t left = row[(c - 1 + wq) % wq];
      uint64_t right = row[(c + 1) % wq];
      uint64_t west = (self << 1) | (left >> 63);
      uint64_t east = (self >> 1) | (right << 63);
      uint64_t xy = west ^ east;
      hs0[r * wq + c] = xy ^ self;
      hs1[r * wq + c] = (west & east) | (self & xy);
    }
  }
  // Vertical full-adders over the three row sums; rule on n9.
  for (int64_t r = 0; r < h; ++r) {
    int64_t up = (r - 1 + h) % h, dn = (r + 1) % h;
    for (int64_t c = 0; c < wq; ++c) {
      uint64_t a0 = hs0[up * wq + c], b0 = hs0[r * wq + c],
               c0 = hs0[dn * wq + c];
      uint64_t a1 = hs1[up * wq + c], b1 = hs1[r * wq + c],
               c1 = hs1[dn * wq + c];
      uint64_t xy0 = a0 ^ b0;
      uint64_t u0 = xy0 ^ c0;
      uint64_t u1 = (a0 & b0) | (c0 & xy0);
      uint64_t xy1 = a1 ^ b1;
      uint64_t v0 = xy1 ^ c1;
      uint64_t v1 = (a1 & b1) | (c1 & xy1);
      uint64_t n1 = u1 ^ v0;
      uint64_t c2 = u1 & v0;
      uint64_t n2 = v1 ^ c2;
      uint64_t n3 = v1 & c2;
      uint64_t self = in[r * wq + c];
      // alive' = (n9 == 3) | (alive & n9 == 4)
      out[r * wq + c] =
          ~n3 & ((~n2 & n1 & u0) | (self & n2 & ~n1 & ~u0));
    }
  }
}

}  // extern "C"
