"""A Gosper glider gun firing on a 2^20 x 2^20 torus (2^40 cells — never
materialised: only the live window is). Run:

    python examples/sparse_gun.py [turns]

This drives the sparse kernel directly; since r4 sparse runs also ride
the FULL control protocol (ticker, pause, windowed snapshots, detach,
checkpoints):

    python -m gol_tpu -w 1048576 -h 1048576 --sparse --rle gosper-gun --headless
    gol-tpu-server --sparse 1048576   # remote sparse engine
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from a bare clone

import time

from gol_tpu.models.patterns import pattern_cells
from gol_tpu.models.sparse import SparseTorus


def main() -> None:
    turns = int(sys.argv[1]) if len(sys.argv) > 1 else 3000
    size = 2**20
    sp = SparseTorus(size, pattern_cells("gosper-gun",
                                         at=(size // 2, size // 2)))
    t0 = time.perf_counter()
    sp.run(turns)
    dt = time.perf_counter() - t0
    h, w = sp.window_shape()
    gliders = (sp.alive_count() - 36) // 5  # exact at period-30 phases
    print(f"{turns} turns in {dt:.2f}s ({turns / dt:.0f} turns/s); "
          f"{sp.alive_count()} alive (~{gliders} gliders in flight), "
          f"live window {h}x{w} of {size}x{size}")


if __name__ == "__main__":
    main()
