"""The detach/reattach story in one script: start an engine server, run
"forever", detach with 'q', then a SECOND controller session reattaches
with CONT=yes and finishes the job. Run:

    python examples/detach_resume.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from a bare clone

import queue
import threading
import time

from gol_tpu import Params, events as ev, run
from gol_tpu.engine import Engine
from gol_tpu.server import EngineServer


def main() -> None:
    os.environ["GOL_SERVER_EXIT_ON_KILL"] = "0"
    srv = EngineServer(port=0, host="127.0.0.1", engine=Engine())
    srv.start_background()
    os.environ["SER"] = f"127.0.0.1:{srv.port}"

    # Controller 1: run "forever", detach after a few seconds.
    p1 = Params(threads=8, image_width=64, image_height=64, turns=10**8)
    q1, keys1 = queue.Queue(), queue.Queue()
    t1 = run(p1, q1, keys1)
    time.sleep(4.0)
    keys1.put("q")
    t1.join(60)
    fin1 = [e for e in ev.drain(q1)
            if isinstance(e, ev.FinalTurnComplete)][0]
    print(f"controller 1 detached at turn {fin1.completed_turns}; "
          f"engine keeps the board")

    # Controller 2: reattach and run 1000 more turns.
    os.environ["CONT"] = "yes"
    p2 = Params(threads=8, image_width=64, image_height=64,
                turns=fin1.completed_turns + 1000)
    q2 = queue.Queue()
    run(p2, q2, None).join(120)
    fin2 = [e for e in ev.drain(q2)
            if isinstance(e, ev.FinalTurnComplete)][0]
    print(f"controller 2 resumed and finished at turn "
          f"{fin2.completed_turns} ({len(fin2.alive)} alive)")
    os.environ.pop("CONT", None)
    os.environ.pop("SER", None)
    srv.shutdown()


if __name__ == "__main__":
    main()
