"""Minimal API usage: evolve a board in-process and read the event
stream. Run:  python examples/basic_run.py [rulestring]

The same five lines drive a remote engine instead when SER=host:port is
set (start one with `gol-tpu-server`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from a bare clone

import queue

from gol_tpu import Params, events as ev, run
from gol_tpu.models.lifelike import LifeLikeRule


def main() -> None:
    rule = LifeLikeRule(sys.argv[1]) if len(sys.argv) > 1 else None
    p = Params(threads=8, image_width=64, image_height=64, turns=100)
    q = queue.Queue()
    run(p, q, None, rule=rule)  # images/64x64.pgm -> out/64x64x100.pgm
    for e in ev.drain(q):
        if isinstance(e, (ev.AliveCellsCount, ev.FinalTurnComplete,
                          ev.ImageOutputComplete)):
            print(f"turn {e.completed_turns:>4}: {e}" if str(e)
                  else f"turn {e.completed_turns:>4}: final "
                       f"({len(e.alive)} alive)")


if __name__ == "__main__":
    main()
