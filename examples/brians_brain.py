"""Brian's Brain ('/2/3') — the Generations multi-state family on the
bit-plane packed kernel. Run:  python examples/brians_brain.py [turns]

This drives the kernel directly; since r4 the family also rides the
FULL interactive stack (ticker, pause, snapshot, detach, checkpoints):

    python -m gol_tpu -w 512 -h 512 --rule /2/3 --headless --turns 100
    gol-tpu-server --rule /2/3     # remote engine, same contract
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from a bare clone

import time

import numpy as np

from gol_tpu.models.generations import BRIANS_BRAIN, GenerationsTorus


def main() -> None:
    turns = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    rng = np.random.default_rng(0)
    board = rng.integers(0, 3, size=(1024, 1024)).astype(np.uint8)
    # Warm the exact program that will be timed (the kernel is compiled
    # per static turn count), then time a fresh board.
    GenerationsTorus(board, BRIANS_BRAIN).run(turns)
    gt = GenerationsTorus(board, BRIANS_BRAIN)
    t0 = time.perf_counter()
    gt.run(turns)
    firing = gt.alive_count()
    dt = time.perf_counter() - t0
    print(f"{turns} turns of 1024² Brian's Brain in {dt:.2f}s "
          f"({turns / dt:.0f} turns/s); {firing} cells firing "
          f"({'packed bit-plane' if gt._packed else 'uint8'} kernel)")


if __name__ == "__main__":
    main()
